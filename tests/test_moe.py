"""Expert-parallel MoE dispatch (VERDICT r1 item 4; reference:
incubate/distributed/models/moe/moe_layer.py:260 global_scatter/global_gather
dispatch, paddle/fluid/operators/collective/global_scatter_op.cu.cc)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import parallel
from paddle_tpu.parallel.moe import moe_mlp_arrays, moe_capacity


def _rand_moe(seed, B=2, S=8, H=16, M=32, E=4):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, S, H).astype(np.float32))
    gl = jnp.asarray(rng.randn(B, S, E).astype(np.float32))
    w_in = jnp.asarray(rng.randn(E, H, M).astype(np.float32) * 0.05)
    w_out = jnp.asarray(rng.randn(E, M, H).astype(np.float32) * 0.05)
    return x, gl, w_in, w_out


def _naive_topk(x, gl, w_in, w_out, k):
    """Dense oracle: every token runs its top-k experts, no capacity."""
    probs = jax.nn.softmax(gl, -1)
    topv, topi = jax.lax.top_k(probs, k)
    topv = topv / topv.sum(-1, keepdims=True)
    B, S, H = x.shape
    out = np.zeros((B, S, H), np.float32)
    for b in range(B):
        for s in range(S):
            for j in range(k):
                e = int(topi[b, s, j])
                hid = jax.nn.gelu(x[b, s] @ w_in[e], approximate=True)
                out[b, s] += float(topv[b, s, j]) * np.asarray(hid @ w_out[e])
    return out


def test_moe_matches_dense_oracle_with_ample_capacity():
    x, gl, w_in, w_out = _rand_moe(0)
    y, aux = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(y), _naive_topk(x, gl, w_in, w_out, 2),
                               rtol=1e-4, atol=1e-4)
    assert float(aux) > 0.0  # load-balance loss populated


def test_moe_capacity_drops_overflow_tokens():
    x, gl, w_in, w_out = _rand_moe(1)
    # capacity 1 per expert: most tokens dropped, output far from oracle but
    # finite, and dropped tokens contribute exactly zero
    y, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=0.125)
    assert moe_capacity(16, 4, 2, 0.125) == 1
    assert np.isfinite(np.asarray(y)).all()
    full, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=4.0)
    assert float(jnp.abs(y).sum()) < float(jnp.abs(full).sum())


def test_moe_expert_parallel_matches_single_device():
    x, gl, w_in, w_out = _rand_moe(2)
    y1, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=4.0)
    parallel.init_mesh(dp=2, ep=2, mp=2)
    y2, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-4)


def test_moe_indivisible_batch_warns_and_falls_back():
    """VERDICT r2 weak #5: the local-dense fallback must be loud."""
    x, gl, w_in, w_out = _rand_moe(7, B=3)  # 3 % ep(2) != 0
    parallel.init_mesh(dp=2, ep=2, mp=2)
    y1, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2, capacity_factor=4.0)
    with pytest.warns(UserWarning, match="LOCAL DENSE"):
        y2, _ = moe_mlp_arrays(x, gl, w_in, w_out, top_k=2,
                               capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-4)


def test_moe_dispatch_engages_on_divisible_batch():
    """With batch % ep == 0, the expert-parallel path must actually run
    the global_scatter/global_gather all_to_all pair (not dense fallback)."""
    import warnings as _warnings

    x, gl, w_in, w_out = _rand_moe(8, B=4)
    parallel.init_mesh(dp=2, ep=2, mp=2)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)  # no fallback warning
        hlo = jax.jit(
            lambda *a: moe_mlp_arrays(*a, top_k=2, capacity_factor=4.0)
        ).lower(x, gl, w_in, w_out).as_text()
    assert "all_to_all" in hlo


def test_moe_flops_independent_of_num_experts():
    """Per-token expert FLOPs must not scale with E (the r1 dense MoE was
    O(E) per token). Compare compiled FLOPs at E=4 vs E=16 with fixed k:
    anything > ~1.5x means dense-dispatch asymptotics crept back."""
    def build(E):
        x, gl, w_in, w_out = _rand_moe(3, E=E)
        f = jax.jit(lambda *a: moe_mlp_arrays(*a, top_k=2,
                                              capacity_factor=1.0)[0])
        return f.lower(x, gl, w_in, w_out).compile().cost_analysis()

    c4, c16 = build(4), build(16)
    if not c4 or "flops" not in c4:
        pytest.skip("cost_analysis unavailable on this backend")
    assert c16["flops"] < 1.5 * c4["flops"], (c4["flops"], c16["flops"])


def test_gpt_moe_aux_loss_exposed():
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    paddle.seed(0)
    cfg = gpt_test_config(moe_every_n=2, moe_num_experts=4,
                          sequence_parallel=False)
    model = GPTForCausalLM(cfg)
    ids = Tensor(jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 8)), jnp.int32))
    _ = model(ids)
    moe_blocks = [blk for blk in model.gpt.h
                  if type(blk.mlp).__name__ == "GPTMoEMLP"]
    assert moe_blocks and all(b.mlp.aux_loss is not None for b in moe_blocks)


def test_incubate_moe_layer_capacity_and_parity():
    """incubate MoELayer (reference moe_layer.py:260): with generous
    capacity and top_k=E, the combine reproduces the dense prob-weighted
    mixture of experts."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    paddle.seed(1)
    d, E = 8, 2
    experts = [paddle.nn.Linear(d, d) for _ in range(E)]
    moe = MoELayer(d_model=d, experts=experts, gate="naive", top_k=E)
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, d)
                         .astype("float32"))
    y = moe(x).numpy()
    # dense reference: softmax(gate) weighted sum of all experts
    import jax
    logits = moe.gate(x).numpy()
    probs = np.asarray(jax.nn.softmax(logits, -1))
    dense = sum(probs[:, e:e + 1] * experts[e](x).numpy() for e in range(E))
    np.testing.assert_allclose(y, dense, rtol=1e-4, atol=1e-5)


def test_incubate_moe_gates_and_aux():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.models.moe import (
        GShardGate, MoELayer, NaiveGate, SwitchGate)

    paddle.seed(0)
    d = 8
    experts = [paddle.nn.Linear(d, d) for _ in range(4)]
    x = paddle.to_tensor(np.random.RandomState(3).randn(3, 5, d)
                         .astype("float32"))
    for gate in ("naive", "gshard", "switch",
                 GShardGate(d, 4), {"type": "switch"}):
        moe = MoELayer(d_model=d, experts=experts, gate=gate)
        out = moe(x)
        assert out.shape == (3, 5, d)
        assert np.isfinite(float(moe.l_aux))
    import pytest as _pytest

    with _pytest.raises(TypeError):
        MoELayer(d_model=d, experts=experts, gate=123)


def test_incubate_moe_gate_config_honored():
    import numpy as np
    import pytest as _pytest
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.models.moe import MoELayer, SwitchGate

    d = 8
    experts = [paddle.nn.Linear(d, d) for _ in range(2)]
    moe = MoELayer(d_model=d, experts=experts,
                   gate={"type": "switch", "switch_eps": 0.3})
    assert moe.gate.switch_eps == 0.3 and moe.top_k == 1
    with _pytest.raises(ValueError):
        SwitchGate(d, 2, top_k=2)
    with _pytest.raises(ValueError):
        MoELayer(d_model=d, experts=[paddle.nn.Linear(d, d)], top_k=2)
    # training jitter changes routing-noise determinism only in train mode
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, d).astype("float32"))
    moe.eval()
    np.testing.assert_allclose(moe(x).numpy(), moe(x).numpy())


def test_moe_routing_utils_reference_examples():
    """number_count / assign_pos / limit_by_capacity /
    prune_gate_by_capacity / random_routing (reference
    distributed/models/moe/utils.py) — asserted against the reference
    docstrings' own worked examples."""
    from paddle_tpu.distributed.utils import (
        assign_pos, limit_by_capacity, number_count, prune_gate_by_capacity,
        random_routing)

    numbers = paddle.to_tensor(np.array([[0, 2], [0, 2]], np.int32))
    np.testing.assert_array_equal(number_count(numbers, 6).numpy(),
                                  [2, 0, 2, 0, 0, 0])

    cum = paddle.to_tensor(np.cumsum([2, 0, 2, 0]).astype(np.int64))
    np.testing.assert_array_equal(assign_pos(numbers, cum).numpy(),
                                  [2, 0, 3, 1])

    ec = paddle.to_tensor(np.array([1, 2, 2, 8, 3, 6], np.int32))
    cap = paddle.to_tensor(np.array([5, 5, 5], np.int32))
    np.testing.assert_array_equal(limit_by_capacity(ec, cap, 2).numpy(),
                                  [1, 2, 2, 4, 3, 3])

    gate = paddle.to_tensor(np.array([1, 3, 3, 3, 3, 2, 1, 1], np.int32))
    ec2 = paddle.to_tensor(np.array([0, 3, 1, 3, 0, 0, 0, 0], np.int32))
    np.testing.assert_array_equal(
        prune_gate_by_capacity(gate, ec2, 8, 1).numpy(),
        [1, 3, 3, 3, -1, 2, 1, 1])

    idx = paddle.to_tensor(np.array([[0, 1], [2, 3]], np.int32))
    val = paddle.to_tensor(np.array([[0.6, 0.4], [0.9, 0.05]], np.float32))
    prob = paddle.to_tensor(np.array([0.5, 0.5], np.float32))
    np.testing.assert_array_equal(random_routing(idx, val, prob).numpy(),
                                  [[0, 1], [2, -1]])

    # jit-safe: the whole pipeline compiles (static shapes)
    import jax

    def pipeline(nums):
        c = number_count(paddle.Tensor(nums), 4)
        cum2 = paddle.Tensor(jnp.cumsum(c._data))
        return assign_pos(paddle.Tensor(nums), cum2)._data

    out = jax.jit(pipeline)(jnp.asarray([[1, 0], [3, 1]], jnp.int32))
    assert np.asarray(out).shape == (4,)
