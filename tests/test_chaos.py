"""Chaos engineering (ISSUE 18) — deterministic network-fault family.

Fast tier, subprocess-free: FaultPlan per-kind spec validation, the
seeded `p=` replay pin (same schedule + seed ⇒ bit-identical fire
sequence), and the rpc choke points driven over socketpairs — garble
corrupts, delay trickles, drop/partition raise, a garbled frame gets a
structured error reply from the server handler instead of killing it,
and the post-dial send/recv budget is bounded by the shared Deadline.

The cross-process half — router + 4 replicas through a scripted fault
schedule (drop, delay, partition, garble, stall, SIGKILL) asserting
no-hang / token-identity / zero KV leaks — is scripts/chaos_smoke.py,
run by the slow-tier test at the bottom.
"""
import os
import pathlib
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from paddle_tpu import monitor
from paddle_tpu.distributed import rpc as rpc_mod
from paddle_tpu.monitor import flight
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.faults import FaultPlan
from paddle_tpu.resilience.retry import Deadline


@pytest.fixture(autouse=True)
def _fresh():
    faults.set_plan(None)
    monitor.reset()
    flight.get_recorder().clear()
    yield
    faults.set_plan(None)
    monitor.reset()
    flight.get_recorder().clear()


# ---------------------------------------------------------------------------
# FaultPlan: per-kind key validation, times=0, multi-spec plans
# ---------------------------------------------------------------------------

def test_per_kind_key_validation():
    # valid for one kind, rejected for another — loudly, at parse time
    FaultPlan("net_delay@site=rpc.send,secs=0.1")
    FaultPlan("stall@site=engine.step,secs=9")
    with pytest.raises(ValueError, match="unknown key"):
        FaultPlan("net_drop@secs=1")           # secs: delay/partition only
    with pytest.raises(ValueError, match="unknown key"):
        FaultPlan("stall@peer=r0")             # peer: net_* only
    with pytest.raises(ValueError, match="unknown key"):
        FaultPlan("conn_error@hard=1")         # hard: ckpt_crash only
    with pytest.raises(ValueError, match="unknown key"):
        FaultPlan("net_garble@bogus=1")        # globally unknown
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan("eth_unplug@site=rpc.dial")


def test_times_zero_fires_on_every_match():
    p = FaultPlan("net_drop@site=rpc.dial,times=0")
    for _ in range(25):
        assert p.net_fire(site="rpc.dial") is not None
    # bounded budget burns out; times=0 (above) never did
    q = FaultPlan("net_drop@site=rpc.dial,times=2")
    assert q.net_fire(site="rpc.dial") is not None
    assert q.net_fire(site="rpc.dial") is not None
    assert q.net_fire(site="rpc.dial") is None


def test_multi_spec_same_kind_different_sites():
    p = FaultPlan("net_drop@site=rpc.send,times=1;"
                  "net_drop@site=rpc.recv,times=1")
    assert p.net_fire(site="rpc.recv").kind == "net_drop"
    assert p.net_fire(site="rpc.recv") is None     # that site's burned
    assert p.net_fire(site="rpc.send").kind == "net_drop"
    assert p.net_fire(site="rpc.send") is None


def test_peer_addressing_is_one_directional():
    p = FaultPlan("net_partition@peer=r2,times=0")
    assert p.net_fire(site="rpc.dial", peer="r2") is not None
    assert p.net_fire(site="rpc.send", peer="r2") is not None
    assert p.net_fire(site="rpc.send", peer="r0") is None
    assert p.net_fire(site="rpc.send") is None     # peerless call sites


def test_kinds_filter_protects_budget():
    # a garble spec consulted at dial (where there is no payload) must
    # neither fire nor burn its budget
    p = FaultPlan("net_garble@times=1")
    assert p.net_fire(site="rpc.dial",
                      kinds=("net_drop", "net_delay",
                             "net_partition")) is None
    assert p.net_fire(site="rpc.send").kind == "net_garble"


def test_seeded_probability_replays_bit_identical():
    spec = "net_drop@site=rpc.send,p=0.4,seed=7,times=0"
    calls = [("rpc.send", peer) for peer in ("r0", "r1", "r2", "r3")] * 25

    def run(plan):
        return [plan.net_fire(site=s, peer=pr) is not None
                for s, pr in calls]

    seq_a = run(FaultPlan(spec))
    seq_b = run(FaultPlan(spec))
    assert seq_a == seq_b                      # the replay pin
    assert any(seq_a) and not all(seq_a)       # p actually gates
    # a different seed produces a different (still deterministic) pattern
    seq_c = run(FaultPlan("net_drop@site=rpc.send,p=0.4,seed=8,times=0"))
    assert seq_c == run(
        FaultPlan("net_drop@site=rpc.send,p=0.4,seed=8,times=0"))
    assert seq_c != seq_a


def test_fires_count_metric_and_flight_breadcrumbs():
    p = FaultPlan("net_garble@site=rpc.recv,times=2")
    assert p.net_fire(site="rpc.recv") is not None
    assert p.net_fire(site="rpc.recv") is not None
    notes = [r for r in flight.get_recorder().records()
             if r.get("event") == "fault/injected"]
    assert len(notes) == 2
    assert notes[0]["fault"] == "net_garble"
    assert notes[0]["site"] == "rpc.recv"


def test_get_plan_disabled_path_caches(monkeypatch):
    monkeypatch.delenv("PTPU_FAULTS", raising=False)
    faults.set_plan(None)
    assert faults.get_plan() is None
    assert faults.net_fire(site="rpc.send") is None
    # resolved-to-None is cached: the hot path is one global read, so a
    # later env write is invisible until set_plan(None) re-arms it
    monkeypatch.setenv("PTPU_FAULTS", "net_drop@times=0")
    assert faults.get_plan() is None
    faults.set_plan(None)
    assert faults.get_plan() is not None


# ---------------------------------------------------------------------------
# rpc choke points over socketpairs
# ---------------------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_send_garble_corrupts_frame_deterministically():
    faults.set_plan(FaultPlan("net_garble@site=rpc.send,times=0"))
    payload = pickle.dumps(("fn", (1, 2), {}))
    a, b = _pair()
    with a, b:
        rpc_mod._send_frame(a, payload)
        raw1 = rpc_mod._recv_frame(b)
        rpc_mod._send_frame(a, payload)
        raw2 = rpc_mod._recv_frame(b)
    assert raw1 == raw2 == rpc_mod._garble(payload)   # deterministic
    with pytest.raises(Exception):
        pickle.loads(raw1)                             # and truly garbled


def test_send_drop_and_partition_raise():
    faults.set_plan(FaultPlan("net_drop@site=rpc.send,times=1"))
    a, b = _pair()
    with a, b:
        with pytest.raises(ConnectionResetError):
            rpc_mod._send_frame(a, b"x")
    faults.set_plan(FaultPlan("net_partition@site=rpc.recv,secs=0.05,"
                              "times=1"))
    a, b = _pair()
    with a, b:
        t0 = time.monotonic()
        with pytest.raises(socket.timeout):
            rpc_mod._recv_frame(b)
        assert time.monotonic() - t0 >= 0.04   # blackhole blocked first


def test_send_delay_trickles_but_arrives_intact():
    faults.set_plan(FaultPlan("net_delay@site=rpc.send,secs=0.12,times=1"))
    payload = pickle.dumps(list(range(500)))
    a, b = _pair()
    with a, b:
        t0 = time.monotonic()
        rpc_mod._send_frame(a, payload)
        took = time.monotonic() - t0
        assert rpc_mod._recv_frame(b) == payload       # intact, just slow
    assert took >= 0.1


def test_handler_replies_structured_error_to_garbled_frame():
    """A corrupt frame reaching the server errors THAT request with a
    pickled (False, RuntimeError) reply — the serve thread survives and
    the caller is never left blocked until its timeout."""
    a, b = _pair()
    garbage = b"\x80\x04this is not a pickle"
    a.sendall(struct.pack("<Q", len(garbage)) + garbage)
    t = threading.Thread(target=rpc_mod._handle, args=(b,))
    t.start()
    with a:
        ok, payload = pickle.loads(rpc_mod._recv_frame(a))
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert ok is False
    assert isinstance(payload, RuntimeError)
    assert "garbled rpc frame" in str(payload)


def test_post_dial_budget_bounded_by_deadline():
    """The satellite fix: send/recv socket timeouts re-arm from the
    Deadline's REMAINING budget, not the full timeout again."""
    dl = Deadline(0.5)
    time.sleep(0.1)
    b = rpc_mod._budget(60.0, dl)
    assert b <= 0.45                       # dial time was not refunded
    assert rpc_mod._budget(60.0, Deadline(None)) == 60.0
    time.sleep(0.45)
    assert rpc_mod._budget(60.0, dl) == pytest.approx(1e-3)


# ---------------------------------------------------------------------------
# the cross-process acceptance (slow tier: scripted fault schedule)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_smoke_script():
    """ISSUE 18 acceptance end-to-end: router + 4 replicas through a
    seeded schedule of all four net_* kinds plus a stall and a
    mid-stream SIGKILL — every stream completes or errors inside its
    deadline bound, surviving deterministic requests are token-identical
    to a fault-free run, and no surviving replica leaks KV blocks."""
    script = pathlib.Path(__file__).resolve().parent.parent / \
        "scripts" / "chaos_smoke.py"
    env = dict(os.environ, PTPU_FORCE_PLATFORM="cpu", JAX_PLATFORMS="cpu",
               PTPU_MONITOR="1", PTPU_CHAOS_SEED="7")
    for k in ("PTPU_FAULTS", "PTPU_FLEET_STORE", "PTPU_ROUTER_DISAGG",
              "PTPU_ROUTER_STICKY"):
        env.pop(k, None)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=900)
    tail = proc.stdout[-4000:] + "\n--- stderr ---\n" + proc.stderr[-4000:]
    assert proc.returncode == 0, tail
    assert "CHAOS SMOKE OK" in proc.stdout, tail
