"""deform_conv2d / yolo_loss tests (reference: test_deform_conv2d.py,
test_yolov3_loss_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision.ops import deform_conv2d, yolo_loss


def _rs(seed=0):
    return np.random.RandomState(seed)


def test_deform_conv2d_zero_offset_equals_conv2d():
    """Zero offsets and unit mask reduce exactly to a plain convolution —
    the strongest oracle available without a CUDA reference."""
    r = _rs(1)
    x = paddle.to_tensor(r.randn(2, 4, 8, 8).astype("float32"))
    w = paddle.to_tensor(r.randn(6, 4, 3, 3).astype("float32"))
    b = paddle.to_tensor(r.randn(6).astype("float32"))
    off = paddle.to_tensor(np.zeros((2, 2 * 9, 8, 8), np.float32))
    got = deform_conv2d(x, off, w, bias=b, padding=1)
    want = F.conv2d(x, w, bias=b, padding=1)
    np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-4, atol=1e-4)


def test_deform_conv2d_integer_offset_shifts_sampling():
    """An integer (+1, +1) offset equals convolving the shifted image."""
    r = _rs(2)
    x_np = r.randn(1, 1, 6, 6).astype("float32")
    w = paddle.to_tensor(r.randn(1, 1, 1, 1).astype("float32"))
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[:, 0] = 1.0  # dy = +1
    got = deform_conv2d(paddle.to_tensor(x_np), paddle.to_tensor(off), w)
    # sampling y+1 with zero padding at the bottom edge
    shifted = np.zeros_like(x_np)
    shifted[:, :, :-1] = x_np[:, :, 1:]
    want = shifted * w.numpy().reshape(())
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-5)


def test_deform_conv2d_fractional_offset_numpy_ref():
    """Fractional offsets vs an independent loop-based bilinear reference."""
    r = _rs(3)
    N, C, H, W, Co, K = 1, 2, 5, 5, 3, 3
    x_np = r.randn(N, C, H, W).astype("float32")
    w_np = r.randn(Co, C, K, K).astype("float32")
    off_np = (r.rand(N, 2 * K * K, H, W).astype("float32") - 0.5)

    got = deform_conv2d(paddle.to_tensor(x_np), paddle.to_tensor(off_np),
                        paddle.to_tensor(w_np), padding=1).numpy()

    def sample(img, y, x):
        if y <= -1 or y >= H or x <= -1 or x >= W:
            return 0.0
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        wy, wx = y - y0, x - x0
        val = 0.0
        for (yy, xx, ww) in ((y0, x0, (1 - wy) * (1 - wx)),
                             (y0, x0 + 1, (1 - wy) * wx),
                             (y0 + 1, x0, wy * (1 - wx)),
                             (y0 + 1, x0 + 1, wy * wx)):
            if 0 <= yy < H and 0 <= xx < W:
                val += img[yy, xx] * ww
        return val

    want = np.zeros((N, Co, H, W), np.float32)
    for n in range(N):
        for co in range(Co):
            for ho in range(H):
                for wo in range(W):
                    acc = 0.0
                    for c in range(C):
                        for ki in range(K):
                            for kj in range(K):
                                k = ki * K + kj
                                dy = off_np[n, 2 * k, ho, wo]
                                dx = off_np[n, 2 * k + 1, ho, wo]
                                y = ho - 1 + ki + dy
                                x = wo - 1 + kj + dx
                                acc += w_np[co, c, ki, kj] * sample(
                                    x_np[n, c], y, x)
                    want[n, co, ho, wo] = acc
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_deform_conv2d_mask_and_grads():
    r = _rs(4)
    x = paddle.to_tensor(r.randn(1, 2, 6, 6).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor(r.randn(2, 2, 3, 3).astype("float32"),
                         stop_gradient=False)
    off = paddle.to_tensor(
        (r.rand(1, 18, 6, 6).astype("float32") - 0.5), stop_gradient=False)
    mask = paddle.to_tensor(r.rand(1, 9, 6, 6).astype("float32"))
    out = deform_conv2d(x, off, w, padding=1, mask=mask)
    out.sum().backward()
    for t in (x, w, off):
        assert t.grad is not None
        assert np.isfinite(t.grad.numpy()).all()
    # half mask halves the output
    out2 = deform_conv2d(x, off, w, padding=1,
                         mask=paddle.to_tensor(mask.numpy() * 0.5))
    np.testing.assert_allclose(out2.numpy(), out.numpy() * 0.5,
                               rtol=1e-4, atol=1e-5)


# -- yolo_loss ---------------------------------------------------------------

_ANCHORS = [10, 13, 16, 30, 33, 23]
_MASK = [0, 1, 2]


def _head(seed, N=2, S=3, C=4, H=4, W=4):
    return _rs(seed).randn(N, S * (5 + C), H, W).astype("float32") * 0.1


def test_yolo_loss_shape_and_finite():
    x = paddle.to_tensor(_head(5))
    gt = paddle.to_tensor(np.array(
        [[[0.3, 0.3, 0.2, 0.2], [0.7, 0.6, 0.4, 0.3]],
         [[0.5, 0.5, 0.1, 0.1], [0.0, 0.0, 0.0, 0.0]]], np.float32))
    lab = paddle.to_tensor(np.array([[1, 3], [0, 0]], np.int32))
    loss = yolo_loss(x, gt, lab, _ANCHORS, _MASK, class_num=4,
                     ignore_thresh=0.7, downsample_ratio=32)
    assert loss.shape == (2,)
    assert np.isfinite(loss.numpy()).all()
    assert (loss.numpy() > 0).all()


def test_yolo_loss_empty_gt_only_objectness():
    """No ground truth: the only loss left is negative objectness."""
    x_np = _head(6)
    x = paddle.to_tensor(x_np)
    gt = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
    lab = paddle.to_tensor(np.zeros((2, 3), np.int32))
    loss = yolo_loss(x, gt, lab, _ANCHORS, _MASK, class_num=4,
                     ignore_thresh=0.7, downsample_ratio=32)
    # analytic: sum of BCE(obj_logit, 0) over the grid
    S, C, H, W = 3, 4, 4, 4
    obj = x_np.reshape(2, S, 5 + C, H, W)[:, :, 4]
    want = np.sum(np.maximum(obj, 0) - obj * 0 + np.log1p(np.exp(-np.abs(obj))),
                  axis=(1, 2, 3))
    np.testing.assert_allclose(loss.numpy(), want, rtol=1e-4)


@pytest.mark.slow
def test_yolo_loss_trains():
    """Gradient steps on the head must reduce the loss (end-to-end sanity
    in place of a CUDA-kernel oracle)."""
    from paddle_tpu import optimizer

    head = paddle.to_tensor(_head(7, N=1), stop_gradient=False)
    gt = paddle.to_tensor(np.array([[[0.4, 0.4, 0.3, 0.3]]], np.float32))
    lab = paddle.to_tensor(np.array([[2]], np.int32))

    first = None
    for i in range(60):
        loss = yolo_loss(head, gt, lab, _ANCHORS, _MASK, class_num=4,
                         ignore_thresh=0.7, downsample_ratio=32).sum()
        if first is None:
            first = float(loss)
        loss.backward()
        head.set_value(paddle.to_tensor(head.numpy() - 0.1 * head.grad.numpy()))
        head.clear_grad()
        head.stop_gradient = False
    assert float(loss) < first * 0.5, (first, float(loss))


def test_yolo_loss_gt_score_weights():
    """gt_score scales the positive terms (mixup support)."""
    x = paddle.to_tensor(_head(8, N=1))
    gt = paddle.to_tensor(np.array([[[0.4, 0.4, 0.3, 0.3]]], np.float32))
    lab = paddle.to_tensor(np.array([[2]], np.int32))
    kw = dict(anchors=_ANCHORS, anchor_mask=_MASK, class_num=4,
              ignore_thresh=0.7, downsample_ratio=32)
    l_full = float(yolo_loss(x, gt, lab, gt_score=paddle.to_tensor(
        np.ones((1, 1), np.float32)), **kw).sum())
    l_half = float(yolo_loss(x, gt, lab, gt_score=paddle.to_tensor(
        np.full((1, 1), 0.5, np.float32)), **kw).sum())
    assert l_half < l_full


def test_yolo_loss_zero_length_gt_dim():
    """B=0 gt tensors must not crash (review regression)."""
    x_np = _head(9)
    loss = yolo_loss(paddle.to_tensor(x_np),
                     paddle.to_tensor(np.zeros((2, 0, 4), np.float32)),
                     paddle.to_tensor(np.zeros((2, 0), np.int32)),
                     _ANCHORS, _MASK, class_num=4, ignore_thresh=0.7,
                     downsample_ratio=32)
    obj = x_np.reshape(2, 3, 9, 4, 4)[:, :, 4]
    want = np.sum(np.maximum(obj, 0) + np.log1p(np.exp(-np.abs(obj))),
                  axis=(1, 2, 3))
    np.testing.assert_allclose(loss.numpy(), want, rtol=1e-4)


@pytest.mark.slow
def test_yolo_loss_mixup_objectness_targets_one():
    """gt_score weights the positive objectness term; the target stays 1.0
    (minimizing with score=0.5 still drives the logit UP, review finding)."""
    head = paddle.to_tensor(_head(10, N=1), stop_gradient=False)
    gt = paddle.to_tensor(np.array([[[0.4, 0.4, 0.3, 0.3]]], np.float32))
    lab = paddle.to_tensor(np.array([[2]], np.int32))
    sc = paddle.to_tensor(np.full((1, 1), 0.5, np.float32))
    for _ in range(80):
        loss = yolo_loss(head, gt, lab, _ANCHORS, _MASK, class_num=4,
                         ignore_thresh=0.7, downsample_ratio=32,
                         gt_score=sc).sum()
        loss.backward()
        head.set_value(paddle.to_tensor(head.numpy() - 0.2 * head.grad.numpy()))
        head.clear_grad()
        head.stop_gradient = False
    # the assigned cell's objectness logit must end up clearly positive
    obj = head.numpy().reshape(1, 3, 9, 4, 4)[:, :, 4]
    assert obj.max() > 1.0, obj.max()


def test_deform_conv2d_layer():
    from paddle_tpu.vision.ops import DeformConv2D

    layer = DeformConv2D(3, 6, 3, padding=1)
    x = paddle.to_tensor(_rs(20).randn(1, 3, 6, 6).astype("float32"))
    off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
    out = layer(x, off)
    assert out.shape == (1, 6, 6, 6)
    want = F.conv2d(x, layer.weight, bias=layer.bias, padding=1)
    np.testing.assert_allclose(out.numpy(), want.numpy(), rtol=1e-4, atol=1e-4)
    assert len(layer.parameters()) == 2


def test_deform_conv2d_layer_is_real_class():
    """Review regression: DeformConv2D must be a plain Layer subclass
    (isinstance, pickling, subclassing all work)."""
    import pickle
    from paddle_tpu.nn.layer import Layer
    from paddle_tpu.vision.ops import DeformConv2D

    layer = DeformConv2D(2, 2, 3)
    assert isinstance(layer, DeformConv2D)
    assert isinstance(layer, Layer)
    assert type(DeformConv2D(2, 2, 3)) is type(layer)
    blob = pickle.dumps(layer.state_dict())
    assert pickle.loads(blob)
