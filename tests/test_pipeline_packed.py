"""Packed-sequence (segment-id) inputs through the pipeline axis.

Reference capability class: packed pretraining batches are the standard
TPU input format (SURVEY §5.7); the reference carries attention metadata
with activations through its p2p pipeline (pp_utils/p2p_communication.py
meta handshake). Here the id rows ride `parallel/pipeline.py`'s aux
threading: split with the activation micro-batches, replicated across
stages, indexed by the in-flight micro-batch — through gpipe, the
interleaved schedule, and the fused 1F1B loss.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.parallel.pipeline import pipeline_apply, scan_blocks
from paddle_tpu.models import GPTForCausalLM, gpt_test_config

import pytest

pytestmark = pytest.mark.slow


def _block_aux(p, h, aux):
    # aux enters the block so wrong micro-batch pairing shows up as a
    # numeric mismatch, not a silent no-op
    return jnp.tanh(h @ p["w"] + p["b"]) + 0.1 * aux


def _toy_setup(seed=0, L=8, H=16, B=8):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3,
        "b": jnp.asarray(rng.randn(L, H), jnp.float32) * 0.1,
    }
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    aux = jnp.asarray(rng.randn(B, H), jnp.float32)
    return params, x, aux


def test_gpipe_aux_matches_serial():
    """Every stage must read the aux rows of the micro-batch it is
    computing (stage s at tick t works on micro-batch t-s)."""
    parallel.init_mesh(pp=4)
    mesh = parallel.get_mesh()
    params, x, aux = _toy_setup()
    sharded = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
               for k, v in params.items()}

    out = jax.jit(lambda p, a, s: pipeline_apply(
        _block_aux, p, a, n_microbatches=4, aux=s))(sharded, x, aux)
    ref = jax.jit(lambda p, a, s: scan_blocks(
        _block_aux, p, a, aux=s))(params, x, aux)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    # grads through the aux-fed pipeline still match serial
    def loss_pipe(p, a, s):
        return jnp.sum(pipeline_apply(_block_aux, p, a,
                                      n_microbatches=4, aux=s) ** 2)

    def loss_ser(p, a, s):
        return jnp.sum(scan_blocks(_block_aux, p, a, aux=s) ** 2)

    g1 = jax.jit(jax.grad(loss_pipe))(sharded, x, aux)
    g2 = jax.jit(jax.grad(loss_ser))(params, x, aux)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   atol=1e-4)


def test_interleaved_aux_matches_serial():
    """Virtual-stage schedule: unit k on device s at slot u=k+s must look
    up micro-batch f(k)'s aux rows."""
    parallel.init_mesh(pp=2)
    mesh = parallel.get_mesh()
    params, x, aux = _toy_setup(seed=3)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
               for k, v in params.items()}
    out = jax.jit(lambda p, a, s: pipeline_apply(
        _block_aux, p, a, n_microbatches=4, num_chunks=2, aux=s))(
            sharded, x, aux)
    ref = jax.jit(lambda p, a, s: scan_blocks(
        _block_aux, p, a, aux=s))(params, x, aux)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def _packed_batch(batch=8, seq=32, vocab=128, seed=9):
    """Every row packs two documents with a random boundary; positions
    restart at the boundary (the standard packed pretraining triple)."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, vocab, (batch, seq)).astype("int32")
    cut = rng.randint(seq // 4, 3 * seq // 4, size=(batch,))
    ar = np.arange(seq)[None, :]
    seg = (ar >= cut[:, None]).astype(np.int32)
    pos = np.where(seg == 0, ar, ar - cut[:, None]).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype("int32")
    return ids, labels, seg, pos


def _packed_losses(mesh_kwargs, schedule="gpipe", chunks=1, steps=3):
    paddle.seed(42)
    parallel.init_mesh(**mesh_kwargs)
    cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True,
                          pp_schedule=schedule, pp_num_chunks=chunks,
                          pp_num_microbatches=2 if chunks > 1 else 0)
    model = parallel.place_model(GPTForCausalLM(cfg))
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y, seg, pos):
        loss = model.pretrain_loss(x, y, segment_ids=seg, position_ids=pos)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    ids, labels, seg, pos = _packed_batch(vocab=128)
    args = [paddle.to_tensor(a) for a in (ids, labels, seg, pos)]
    return [float(compiled(*args)) for _ in range(steps)]


def test_packed_gpipe_pp2_matches_pp1():
    """VERDICT r4 item 5 bar: packed pp2 parity vs pp1 (gpipe forward —
    ids ride pipeline_apply aux)."""
    ref = _packed_losses(dict())
    pp2 = _packed_losses(dict(pp=2))
    np.testing.assert_allclose(pp2, ref, rtol=2e-4)


def test_packed_interleave_matches_pp1():
    ref = _packed_losses(dict(), chunks=1)
    il = _packed_losses(dict(pp=2), chunks=2)
    np.testing.assert_allclose(il, ref, rtol=2e-4)


def test_packed_1f1b_matches_pp1():
    """Fused 1F1B loss with packed ids: forward slot f and the
    recompute-backward slot b both read their own id rows."""
    ref = _packed_losses(dict(), schedule="1f1b")
    pp2 = _packed_losses(dict(pp=2), schedule="1f1b")
    np.testing.assert_allclose(pp2, ref, rtol=2e-4)


def test_packed_pp_attention_isolation():
    """The loss-level parity above could in principle hide a mask bug that
    cancels in the mean; check logits directly: a packed pp2 forward must
    equal running each document alone (no cross-document attention
    through the pipeline)."""
    paddle.seed(11)
    parallel.init_mesh(pp=2)
    cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True,
                          hidden_size=128, intermediate_size=256,
                          num_attention_heads=2,
                          max_position_embeddings=64)
    m = parallel.place_model(GPTForCausalLM(cfg))
    m.eval()
    rs = np.random.RandomState(5)
    la, lb, B = 10, 6, 4
    doc_a = rs.randint(1, 100, (B, la)).astype("int32")
    doc_b = rs.randint(1, 100, (B, lb)).astype("int32")
    packed = np.concatenate([doc_a, doc_b], axis=1)
    seg = np.tile(np.array([[0] * la + [1] * lb], np.int32), (B, 1))
    pos = np.tile(np.array([list(range(la)) + list(range(lb))], np.int32),
                  (B, 1))

    out = m(paddle.to_tensor(packed), position_ids=paddle.to_tensor(pos),
            segment_ids=paddle.to_tensor(seg)).numpy()
    out_a = m(paddle.to_tensor(doc_a)).numpy()
    out_b = m(paddle.to_tensor(doc_b)).numpy()
    np.testing.assert_allclose(out[:, :la], out_a, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out[:, la:], out_b, rtol=2e-4, atol=2e-4)
