"""Full namespace-parity sweep (AST-parsed reference __all__ lists,
including += aug-assigns) + behavior checks for the final long-tail batch:
vision transforms/models/datasets, audio IO, distributed compat, text
datasets, profiler enums."""
import ast
import pathlib
import importlib

import numpy as np
import pytest

import paddle_tpu as paddle


def _ref_all(rel):
    p = pathlib.Path("/root/reference") / rel
    if not p.exists():
        return None
    names = []
    tree = ast.parse(p.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        names += [ast.literal_eval(e) for e in node.value.elts]
                    except Exception:
                        pass
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == "__all__":
                try:
                    names += [ast.literal_eval(e) for e in node.value.elts]
                except Exception:
                    pass
    return names


SWEEP = [
    ("distributed", "python/paddle/distributed/__init__.py"),
    ("vision", "python/paddle/vision/__init__.py"),
    ("vision.transforms", "python/paddle/vision/transforms/__init__.py"),
    ("vision.models", "python/paddle/vision/models/__init__.py"),
    ("vision.datasets", "python/paddle/vision/datasets/__init__.py"),
    ("audio", "python/paddle/audio/__init__.py"),
    ("utils", "python/paddle/utils/__init__.py"),
    ("text", "python/paddle/text/__init__.py"),
    ("profiler", "python/paddle/profiler/__init__.py"),
    ("amp", "python/paddle/amp/__init__.py"),
    ("distribution", "python/paddle/distribution/__init__.py"),
]


@pytest.mark.parametrize("name,rel", SWEEP, ids=[m for m, _ in SWEEP])
def test_namespace_covered(name, rel):
    names = _ref_all(rel)
    if names is None:
        pytest.skip("reference tree not available")
    target = importlib.import_module("paddle_tpu." + name)
    missing = sorted(n for n in set(names) if not hasattr(target, n))
    assert missing == [], missing


def test_transform_color_and_geometry():
    from paddle_tpu.vision import transforms as T

    rs = np.random.RandomState(0)
    img = (rs.rand(3, 16, 16) * 255).astype(np.uint8)
    np.testing.assert_allclose(T.adjust_brightness(img, 1.0),
                               img.astype(np.float32), atol=1e-4)
    dark = T.adjust_brightness(img, 0.5)
    assert dark.mean() < img.mean()
    g = T.to_grayscale(img, 3)
    assert g.shape == (3, 16, 16) and np.allclose(g[0], g[1])
    h = T.adjust_hue(img, 0.25)
    assert h.shape == img.shape
    # identity affine returns the image
    ident = T.affine(img.astype(np.float32), 0, (0, 0), 1.0, (0, 0))
    np.testing.assert_allclose(ident, img.astype(np.float32), atol=1e-3)
    rot = T.rotate(img.astype(np.float32), 90)
    assert rot.shape == img.shape
    er = T.erase(img, 2, 2, 4, 4, 0)
    assert (er[:, 2:6, 2:6] == 0).all()
    out = T.RandomResizedCrop(8)(img)
    assert out.shape == (3, 8, 8)
    out = T.RandomErasing(prob=1.0)(img.astype(np.float32))
    assert out.shape == img.shape
    out = T.ColorJitter(0.4, 0.4, 0.4, 0.2)(img)
    assert out.shape == img.shape
    out = T.RandomPerspective(prob=1.0)(img)
    assert out.shape == img.shape
    # (left, top, right, bottom) per the reference convention
    pads = T.pad(img, [1, 2, 3, 4])
    assert pads.shape == (3, 16 + 2 + 4, 16 + 1 + 3)
    # identity perspective
    pts = [(0, 0), (15, 0), (15, 15), (0, 15)]
    np.testing.assert_allclose(T.perspective(img.astype(np.float32), pts, pts),
                               img.astype(np.float32), atol=1e-2)


def test_resnext_groups_actually_differ():
    paddle.seed(0)
    a = paddle.vision.models.resnext50_32x4d(num_classes=4)
    b = paddle.vision.models.resnet50(num_classes=4)
    # grouped conv weight shapes differ from vanilla bottleneck
    wa = {n: tuple(p.shape) for n, p in a.named_parameters()}
    wb = {n: tuple(p.shape) for n, p in b.named_parameters()}
    assert wa != wb
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 32, 32)
                         .astype("float32"))
    assert a(x).shape == (1, 4)
    assert paddle.vision.models.wide_resnet50_2(num_classes=4)(x).shape == (1, 4)


def test_flowers_voc_datasets():
    f = paddle.vision.datasets.Flowers(mode="test")
    img, lab = f[0]
    assert img.shape == (32, 32, 3) and 0 <= lab < 102
    v = paddle.vision.datasets.VOC2012()
    img, mask = v[3]
    assert mask.shape == (32, 32) and mask.max() < 21


def test_audio_roundtrip_and_datasets(tmp_path):
    sr = 8000
    t = np.arange(sr, dtype=np.float32) / sr
    wav = paddle.to_tensor((0.25 * np.sin(2 * np.pi * 440 * t))[None])
    p = str(tmp_path / "a.wav")
    paddle.audio.save(p, wav, sr)
    inf = paddle.audio.info(p)
    assert inf.sample_rate == sr and inf.num_channels == 1
    w2, sr2 = paddle.audio.load(p)
    assert sr2 == sr
    np.testing.assert_allclose(w2.numpy(), wav.numpy(), atol=2e-4)
    # offset/num_frames window
    w3, _ = paddle.audio.load(p, frame_offset=100, num_frames=50)
    assert w3.shape == (1, 50)
    assert "wave" in paddle.audio.backends.list_available_backends()
    ds = paddle.audio.datasets.ESC50(mode="test")
    w, lab = ds[0]
    assert 0 <= lab < 50 and w.dtype == np.float32


def test_distributed_compat_surface():
    d = paddle.distributed
    assert d.is_available()
    assert d.ParallelMode.DATA_PARALLEL == 0
    t = paddle.to_tensor(np.ones(2, np.float32))
    task = d.isend(t, dst=0)
    assert task.wait() and task.is_completed()
    objs = [{"a": 1}, "txt"]
    out = d.broadcast_object_list(objs, src=0)
    assert out[0] == {"a": 1}
    got = []
    d.scatter_object_list(got, [[1, 2]], src=0)
    assert got == [[1, 2]]
    with pytest.raises(ValueError):
        d.CountFilterEntry(-1)
    assert d.ProbabilityEntry(0.5)._to_attr().startswith("probability")
    assert d.ShowClickEntry("s", "c")._to_attr() == "show_click_entry:s:c"


def test_inmemory_and_queue_dataset(tmp_path):
    p = tmp_path / "slots.txt"
    p.write_text("1 2 3\n4 5 6\n7 8 9\n")
    ds = paddle.distributed.InMemoryDataset()
    ds.init(batch_size=2)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 3
    batches = list(ds)
    assert len(batches) == 2 and len(batches[0]) == 2
    q = paddle.distributed.QueueDataset()
    q.init(batch_size=2)
    q.set_filelist([str(p)])
    with pytest.raises(RuntimeError):
        q.load_into_memory()
    assert sum(len(b) for b in q) == 3


def test_text_dataset_exports():
    for cls in (paddle.text.Imdb, paddle.text.UCIHousing):
        assert cls is not None
    c = paddle.text.Conll05st(mode="test")
    assert len(c[0]) == 9
    w = paddle.text.WMT14(mode="test")
    src, ti, tn = w[0]
    assert ti[0] == 1 and tn[-1] == 2


def test_profiler_enums_and_protobuf(tmp_path):
    from paddle_tpu import profiler as prof

    assert prof.SortedKeys.CPUTotal == 0
    assert hasattr(prof.SummaryView, "KernelView")
    handler = prof.export_protobuf(str(tmp_path))

    class _P:
        _events = [("op", 1.0)]

    out = handler(_P())
    assert pathlib.Path(out).exists()


def test_transform_review_fixes():
    from paddle_tpu.vision import transforms as T
    from paddle_tpu.vision.models import shufflenet_v2_swish
    from paddle_tpu import nn

    # swish actually wired through the activations
    m = shufflenet_v2_swish(num_classes=2)
    acts = [type(l).__name__.lower() for l in m.sublayers()]
    assert "swish" in acts and "relu" not in acts
    # BaseTransform passes labels through
    img = (np.random.RandomState(0).rand(3, 8, 8) * 255).astype(np.uint8)
    gray, label = T.Grayscale()((img, 7))
    assert label == 7 and gray.shape[0] == 1
    # fill honored on rotate; expand grows the canvas
    white = T.rotate(np.ones((3, 8, 8), np.float32), 45, fill=5.0)
    assert white.max() == 5.0
    big = T.rotate(np.ones((3, 8, 8), np.float32), 45, expand=True)
    assert big.shape[1] > 8 and big.shape[2] > 8
    # sequence shear accepted
    out = T.RandomAffine(degrees=0, shear=[10, 10])(img.astype(np.float32))
    assert out.shape == img.shape
    # random-value erasing writes per-pixel noise on uint8
    np.random.seed(0)
    er = T.RandomErasing(prob=1.0, value="random")(img)
    assert er.shape == img.shape


def test_require_version():
    assert paddle.utils.require_version("0.0.1")
    assert paddle.utils.require_version("0.1", max_version="0.1")
    with pytest.raises(Exception):
        paddle.utils.require_version("99.0.0")


def test_incubate_nn_and_initializer_namespaces():
    for name, rel in [
            ("incubate.nn", "python/paddle/incubate/nn/__init__.py"),
            ("incubate.nn.functional",
             "python/paddle/incubate/nn/functional/__init__.py"),
            ("nn.initializer", "python/paddle/nn/initializer/__init__.py"),
            ("nn.utils", "python/paddle/nn/utils/__init__.py")]:
        names = _ref_all(rel)
        if names is None:
            pytest.skip("reference tree not available")
        target = importlib.import_module("paddle_tpu." + name)
        missing = sorted(n for n in set(names) if not hasattr(target, n))
        assert missing == [], f"{name}: {missing}"


def test_weight_and_spectral_norm():
    from paddle_tpu import nn

    lin = nn.Linear(4, 6)
    w_before = lin.weight.numpy().copy()
    nn.utils.weight_norm(lin, dim=1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
    y1 = lin(x)
    # reparam preserves the function at init
    np.testing.assert_allclose(np.asarray(lin.weight._data), w_before,
                               rtol=1e-5)
    nn.utils.remove_weight_norm(lin)
    np.testing.assert_allclose(lin(x).numpy(), y1.numpy(), rtol=1e-5)
    assert "weight_g" not in dict(lin.named_parameters())

    sn = nn.Linear(4, 4)
    nn.utils.spectral_norm(sn, n_power_iterations=4)
    _ = sn(x)
    s_max = np.linalg.svd(np.asarray(sn.weight._data),
                          compute_uv=False)[0]
    assert s_max < 1.2


def test_bilinear_init_and_global_initializer():
    from paddle_tpu import nn

    init = paddle.nn.initializer.Bilinear()
    w = init((2, 2, 4, 4), "float32")
    # bilinear kernel is symmetric with peak at center
    k = np.asarray(w)[0, 0]
    assert np.allclose(k, k[::-1]) and np.allclose(k, k[:, ::-1])
    paddle.nn.initializer.set_global_initializer(
        paddle.nn.initializer.Constant(0.25))
    try:
        l = nn.Linear(3, 3)
        assert np.allclose(l.weight.numpy(), 0.25)
    finally:
        paddle.nn.initializer.set_global_initializer(None)


def test_fused_layer_classes_and_functional_fmt():
    inc = paddle.incubate.nn
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
    assert inc.FusedLinear(4, 8)(x).shape == (2, 8)
    fb = inc.FusedBiasDropoutResidualLayerNorm(4, dropout_rate=0.0)
    out = fb(x, x)
    np.testing.assert_allclose(out.numpy().mean(-1), 0.0, atol=1e-5)
    enc = inc.FusedTransformerEncoderLayer(8, 2, 16, dropout_rate=0.0)
    seq = paddle.to_tensor(np.random.RandomState(1).randn(2, 5, 8)
                           .astype("float32"))
    assert enc(seq).shape == (2, 5, 8)

    L, E, F_ = 2, 8, 16
    ones = lambda n: paddle.to_tensor(np.ones(n, np.float32))  # noqa: E731
    zeros = lambda n: paddle.to_tensor(np.zeros(n, np.float32))  # noqa: E731
    mk = lambda *s: paddle.to_tensor(  # noqa: E731
        np.random.RandomState(sum(s)).randn(*s).astype("float32") * 0.05)
    src = paddle.to_tensor(np.random.RandomState(2).randn(1, 4, E)
                           .astype("float32"))
    out = paddle.incubate.nn.functional.fused_multi_transformer(
        src, [ones(E)] * L, [zeros(E)] * L, [mk(E, 3 * E)] * L,
        [zeros(3 * E)] * L, [mk(E, E)] * L, [zeros(E)] * L, [ones(E)] * L,
        [zeros(E)] * L, [mk(E, F_)] * L, [zeros(F_)] * L, [mk(F_, E)] * L,
        [zeros(E)] * L,
        cache_kvs=[paddle.to_tensor(np.zeros((2, 1, 2, 16, 4), np.float32))
                   for _ in range(L)],
        time_step=0)
    o = out[0] if isinstance(out, tuple) else out
    assert o.shape == (1, 4, E) and np.isfinite(o.numpy()).all()


def test_remove_weight_norm_keeps_training_live():
    from paddle_tpu import nn

    lin = nn.Linear(3, 3)
    nn.utils.weight_norm(lin)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    _ = lin(x)
    nn.utils.remove_weight_norm(lin)
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=lin.parameters())
    y0 = lin(x).numpy().copy()
    loss = (lin(x) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    # the update must be visible through the layer's forward (the hook's
    # stale instance attribute used to shadow the restored Parameter)
    assert not np.allclose(lin(x).numpy(), y0)


def test_weight_norm_dim_none_scalar_g():
    from paddle_tpu import nn

    lin = nn.Linear(4, 6)
    w = np.asarray(lin.weight._data)
    nn.utils.weight_norm(lin, dim=None)
    g = np.asarray(lin.weight_g._data)
    assert g.size == 1
    np.testing.assert_allclose(float(g.ravel()[0]), np.linalg.norm(w),
                               rtol=1e-5)


def test_spectral_norm_zero_iterations():
    from paddle_tpu import nn

    sn = nn.Linear(4, 4)
    nn.utils.spectral_norm(sn, n_power_iterations=0)
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    assert np.isfinite(sn(x).numpy()).all()


def test_spectral_norm_frozen_u_is_deterministic():
    from paddle_tpu import nn

    sn = nn.Linear(4, 4)
    nn.utils.spectral_norm(sn, n_power_iterations=0)
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    y1 = sn(x).numpy()
    y2 = sn(x).numpy()
    np.testing.assert_allclose(y1, y2)   # u must not drift per forward


def test_weight_norm_dim_minus_one_is_whole_tensor():
    from paddle_tpu import nn

    lin = nn.Linear(4, 6)
    w = np.asarray(lin.weight._data)
    nn.utils.weight_norm(lin, dim=-1)
    assert np.asarray(lin.weight_g._data).size == 1
    np.testing.assert_allclose(
        float(np.asarray(lin.weight_g._data).ravel()[0]),
        np.linalg.norm(w), rtol=1e-5)


def test_fused_multi_transformer_mode_not_sticky():
    import paddle_tpu.incubate.nn.functional as incF

    L, E, F_ = 1, 8, 16
    ones = lambda n: paddle.to_tensor(np.ones(n, np.float32))  # noqa: E731
    zeros = lambda n: paddle.to_tensor(np.zeros(n, np.float32))  # noqa: E731
    mk = lambda *s: paddle.to_tensor(  # noqa: E731
        np.random.RandomState(sum(s)).randn(*s).astype("float32") * 0.05)
    src = paddle.to_tensor(np.random.RandomState(2).randn(1, 4, E)
                           .astype("float32"))
    args = ([ones(E)] * L, [zeros(E)] * L, [mk(E, 3 * E)] * L,
            [zeros(3 * E)] * L, [mk(E, E)] * L, [zeros(E)] * L,
            [ones(E)] * L, [zeros(E)] * L, [mk(E, F_)] * L, [zeros(F_)] * L,
            [mk(F_, E)] * L, [zeros(E)] * L)
    kw = dict(cache_kvs=[paddle.to_tensor(
        np.zeros((2, 1, 2, 16, 4), np.float32))], time_step=0)
    # eval call first, then a training call with dropout: outputs must
    # DIFFER across training calls (dropout live, mode not sticky)
    incF.fused_multi_transformer(src, *args, dropout_rate=0.5,
                                 training=False, **kw)
    paddle.seed(7)
    o1 = incF.fused_multi_transformer(src, *args, dropout_rate=0.5,
                                      training=True, **kw)
    o2 = incF.fused_multi_transformer(src, *args, dropout_rate=0.5,
                                      training=True, **kw)
    a1 = (o1[0] if isinstance(o1, tuple) else o1).numpy()
    a2 = (o2[0] if isinstance(o2, tuple) else o2).numpy()
    assert not np.allclose(a1, a2)


def test_local_fs_client(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS

    fs = LocalFS()
    d = str(tmp_path / "a" / "b")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = str(tmp_path / "a" / "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    with open(f, "w") as fh:
        fh.write("hello")
    assert fs.cat(f) == "hello"
    dirs, files = fs.ls_dir(str(tmp_path / "a"))
    assert dirs == ["b"] and files == ["x.txt"]
    fs.mv(f, str(tmp_path / "a" / "y.txt"))
    assert fs.is_file(str(tmp_path / "a" / "y.txt"))
    assert fs.list_dirs(str(tmp_path / "a")) == ["b"]
    fs.delete(str(tmp_path / "a"))
    assert not fs.is_exist(str(tmp_path / "a"))
    assert not fs.need_upload_download()

    from paddle_tpu.distributed.fleet.utils import HDFSClient
    with pytest.raises(RuntimeError, match="hadoop"):
        HDFSClient("/nonexistent/hadoop_home")


def test_fleet_inference_quant_namespaces():
    for name, rel in [
            ("distributed.fleet",
             "python/paddle/distributed/fleet/__init__.py"),
            ("inference", "python/paddle/inference/__init__.py"),
            ("quantization", "python/paddle/quantization/__init__.py")]:
        names = _ref_all(rel)
        if names is None:
            pytest.skip("reference tree not available")
        target = importlib.import_module("paddle_tpu." + name)
        missing = sorted(n for n in set(names) if not hasattr(target, n))
        assert missing == [], f"{name}: {missing}"


def test_fleet_topology_and_util():
    from paddle_tpu.distributed import fleet

    t = fleet.CommunicateTopology(dims=[2, 1, 1, 2])
    assert t.world_size() == 4
    assert t.get_rank(data=1, pipe=0, sharding=0, model=0) == 2
    assert t.get_coord(3).model == 1
    assert t.get_axis_list("data", 0) == [0, 1]
    assert [sorted(g) for g in t.get_comm_list("model")] == [[0, 1], [2, 3]]
    u = fleet.UtilBase()
    assert u.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
    f = fleet.Fleet()
    f.init()
    assert f.worker_num() >= 1 and f.util is not None

    class Gen(fleet.MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("w", line.split()), ("y", ["1"])]
            return it

    assert Gen().run_from_memory(["a b"]) == ["2 a b 1 1\n"]


def test_inference_helpers_and_quanter(tmp_path):
    import pickle
    from paddle_tpu import inference, quantization

    assert inference.get_num_bytes_of_data_type(
        inference.DataType.FLOAT16) == 2
    assert inference.get_trt_runtime_version() == (0, 0, 0)
    # mixed-precision conversion of a params blob
    params = {"w": np.ones((4, 4), np.float32), "step": np.int32(3)}
    pf = str(tmp_path / "m.pdiparams")
    mf = str(tmp_path / "m.pdmodel")
    with open(pf, "wb") as f:
        pickle.dump(params, f)
    with open(mf, "wb") as f:
        f.write(b"model")
    inference.convert_to_mixed_precision(
        mf, pf, str(tmp_path / "mm.pdmodel"), str(tmp_path / "mm.pdiparams"),
        mixed_precision=inference.PrecisionType.Bfloat16)
    with open(tmp_path / "mm.pdiparams", "rb") as f:
        out = pickle.load(f)
    assert str(out["w"].dtype) == "bfloat16" and out["step"].dtype.kind == "i"

    @quantization.quanter("SweepQuanter")
    class SweepQuanterLayer:
        def __init__(self, bits=8):
            self.bits = bits

    fac = quantization.SweepQuanter(bits=4)
    assert fac._instance().bits == 4
