"""hapi Model fit/evaluate/predict + callbacks (reference:
python/paddle/hapi/model.py:1004,1696; callbacks.py:551,716)."""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.hapi.callbacks import EarlyStopping, VisualDL
from paddle_tpu.io import TensorDataset
from paddle_tpu.metric import Accuracy


def _toy_data(n=64, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype("float32")
    w = rng.randn(d, classes).astype("float32")
    y = np.argmax(x @ w, axis=1).astype("int64")[:, None]
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _model(d=8, classes=4):
    return nn.Sequential(nn.Linear(d, 32), nn.ReLU(), nn.Linear(32, classes))


def test_fit_evaluate_predict(tmp_path):
    paddle.seed(0)
    x, y = _toy_data()
    ds = TensorDataset([x, y])
    net = _model()
    model = paddle.Model(net)
    model.prepare(
        optimizer=opt.Adam(learning_rate=0.01, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy(),
    )
    hist = model.fit(ds, epochs=8, batch_size=16, verbose=0,
                     save_dir=str(tmp_path / "ckpt"))
    assert hist[-1]["loss"] < hist[0]["loss"]
    ev = model.evaluate(ds, batch_size=16, verbose=0)
    assert ev["acc"] > 0.5
    preds = model.predict(ds, batch_size=16, stack_outputs=True, verbose=0)
    assert preds[0].shape == (64, 4)
    # checkpoints were written
    assert os.path.exists(str(tmp_path / "ckpt" / "final.pdparams"))


def test_save_load_roundtrip(tmp_path):
    paddle.seed(1)
    x, y = _toy_data(seed=1)
    net = _model()
    model = paddle.Model(net)
    model.prepare(optimizer=opt.SGD(0.1, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
    model.train_batch([x], [y])
    path = str(tmp_path / "m")
    model.save(path)

    net2 = _model()
    model2 = paddle.Model(net2)
    model2.prepare(optimizer=opt.SGD(0.1, parameters=net2.parameters()),
                   loss=nn.CrossEntropyLoss())
    model2.load(path)
    a = net(x).numpy()
    b = net2(x).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_early_stopping():
    paddle.seed(2)
    x, y = _toy_data(seed=2)
    ds = TensorDataset([x, y])
    net = _model()
    model = paddle.Model(net)
    # lr=0 -> eval loss plateaus from epoch 1, patience=0 stops immediately
    model.prepare(optimizer=opt.SGD(0.0, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    es = EarlyStopping(monitor="loss", patience=0, verbose=0, save_best_model=False)
    hist = model.fit(ds, eval_data=ds, epochs=50, batch_size=32, verbose=0,
                     callbacks=[es])
    assert len(hist) <= 3  # stopped early


def test_visualdl_scalars(tmp_path):
    paddle.seed(3)
    x, y = _toy_data(seed=3)
    ds = TensorDataset([x, y])
    net = _model()
    model = paddle.Model(net)
    model.prepare(optimizer=opt.SGD(0.05, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
    logdir = str(tmp_path / "vdl")
    model.fit(ds, epochs=2, batch_size=32, verbose=0,
              callbacks=[VisualDL(logdir)])
    content = open(os.path.join(logdir, "scalars.tsv")).read()
    assert "train/loss" in content


def test_summary():
    net = _model()
    info = paddle.summary(net, input_size=(2, 8))
    assert info["total_params"] == 8 * 32 + 32 + 32 * 4 + 4
    assert info["trainable_params"] == info["total_params"]
