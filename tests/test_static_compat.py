"""Static-graph compat surface + module-namespace parity sweep
(reference: python/paddle/static/__init__.py, fft.py, sparse/, jit/,
device/, autograd/saved_tensors_hooks.py)."""
import re
import pathlib
import importlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def test_module_namespaces_covered():
    mods = [("fft", "python/paddle/fft.py"),
            ("static", "python/paddle/static/__init__.py"),
            ("sparse", "python/paddle/sparse/__init__.py"),
            ("geometric", "python/paddle/geometric/__init__.py"),
            ("jit", "python/paddle/jit/__init__.py"),
            ("device", "python/paddle/device/__init__.py"),
            ("io", "python/paddle/io/__init__.py"),
            ("optimizer", "python/paddle/optimizer/__init__.py"),
            ("metric", "python/paddle/metric/__init__.py"),
            ("autograd", "python/paddle/autograd/__init__.py")]
    for name, rel in mods:
        p = pathlib.Path("/root/reference") / rel
        if not p.exists():
            pytest.skip("reference tree not available")
        names = set(re.findall(r"^\s+'([A-Za-z_0-9]+)',", p.read_text(), re.M))
        target = importlib.import_module("paddle_tpu." + name)
        missing = sorted(n for n in names if not hasattr(target, n))
        assert missing == [], f"{name}: {missing}"


def test_static_train_with_compiled_program_and_ema():
    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4])
            y = static.data("y", [None, 1])
            lin = paddle.nn.Linear(4, 1)
            pred = lin(x)
            loss = ((pred - y) ** 2).mean()
            pg = static.append_backward(loss)
            assert len(pg) == 2 and all(g is not None for _, g in pg)
        exe = static.Executor(paddle.CPUPlace())
        compiled = static.CompiledProgram(main).with_data_parallel(
            loss_name="loss", build_strategy=static.BuildStrategy())
        rs = np.random.RandomState(0)
        feed = {"x": rs.randn(8, 4).astype("float32"),
                "y": rs.randn(8, 1).astype("float32")}
        (out,) = exe.run(compiled, feed=feed, fetch_list=[loss])
        assert np.isfinite(out).all()

        ema = static.ExponentialMovingAverage(0.9)
        w0 = lin.weight.numpy().copy()
        ema.update(lin.parameters())
        lin.weight.set_value(w0 + 1.0)
        ema.update(lin.parameters())
        with ema.apply():
            assert not np.allclose(lin.weight.numpy(), w0 + 1.0)
        np.testing.assert_allclose(lin.weight.numpy(), w0 + 1.0)
    finally:
        paddle.disable_static()


def test_static_save_load_roundtrip(tmp_path):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3])
            lin = paddle.nn.Linear(3, 2)
            out = lin(x)
        path = str(tmp_path / "model")
        static.save(main, path)
        w0 = lin.weight.numpy().copy()
        lin.weight.set_value(np.zeros_like(w0))
        static.load(main, path)
        np.testing.assert_allclose(lin.weight.numpy(), w0)
        state = static.load_program_state(path)
        assert any(np.allclose(v, w0) for v in state.values())
        # serialize/deserialize primitives
        blob = static.serialize_persistables(main)
        static.deserialize_persistables(main, blob)
        desc = static.deserialize_program(static.serialize_program(main))
        assert "x" in desc["feeds"]
    finally:
        paddle.disable_static()


def test_normalize_program_prunes():
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2])
            used = x * 2.0
            _unused = x + 100.0
            out = used + 1.0
        n_before = len(main._ops)
        static.normalize_program(main, [x], [out])
        assert len(main._ops) < n_before
        exe = static.Executor()
        (o,) = exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                       fetch_list=[out])
        np.testing.assert_allclose(o, 3.0)
    finally:
        paddle.disable_static()


def test_py_func_forward_and_backward():
    def host_fn(a):
        return a * 2.0

    def host_bwd(a, g):
        return g * 2.0

    x = paddle.to_tensor(np.array([1.0, 3.0], np.float32), stop_gradient=False)
    xx = x * 1.0
    out = paddle.zeros([2], "float32")
    static.py_func(host_fn, xx, out, backward_func=host_bwd)
    np.testing.assert_allclose(out.numpy(), [2.0, 6.0])
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_places_and_scopes():
    assert len(static.cpu_places(3)) == 3
    assert len(static.cuda_places([0])) == 1
    s = static.Scope() if hasattr(static, "Scope") else None
    sc = static.global_scope()
    v = static.create_global_var([2], 1.5, "float32", name="gv")
    assert static.global_scope().find_var("gv") is not None
    with static.device_guard("cpu"):
        pass
    with static.ipu_shard_guard():
        pass


def test_static_metrics():
    probs = paddle.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
    lab = paddle.to_tensor(np.array([[0], [1]], np.int64))
    acc = static.accuracy(probs, lab)
    assert float(acc) == 1.0
    a, b, _ = static.auc(paddle.to_tensor(np.array([[0.3, 0.7], [0.6, 0.4]],
                                                   np.float32)),
                         paddle.to_tensor(np.array([[1], [0]], np.int64)))
    assert 0.0 <= float(a) <= 1.0
    bundle = static.ctr_metric_bundle(
        paddle.to_tensor(np.array([0.8, 0.2], np.float32)),
        paddle.to_tensor(np.array([1.0, 0.0], np.float32)))
    assert len(bundle) == 5


def test_fft_hfft_family():
    rs = np.random.RandomState(0)
    a = rs.randn(4, 6).astype("complex64")
    out = paddle.fft.hfft2(paddle.to_tensor(a))
    ref = np.fft.hfft(np.fft.fft(a, axis=-2), axis=-1)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-3)
    r = rs.randn(4, 6).astype("float32")
    out2 = paddle.fft.ihfft2(paddle.to_tensor(r))
    ref2 = np.fft.ifft(np.fft.ihfft(r, axis=-1), axis=-2)
    np.testing.assert_allclose(out2.numpy(), ref2, rtol=1e-3, atol=1e-4)
    out3 = paddle.fft.hfftn(paddle.to_tensor(a))
    assert out3.shape[-1] == 2 * (a.shape[-1] - 1)
    out4 = paddle.fft.ihfftn(paddle.to_tensor(r))
    assert out4.shape == out2.shape


def test_saved_tensors_hooks_offload():
    calls = {"pack": 0, "unpack": 0}

    def pack(t):
        calls["pack"] += 1
        return np.asarray(t.numpy())

    def unpack(obj):
        calls["unpack"] += 1
        return paddle.to_tensor(obj)

    x = paddle.to_tensor(np.array([0.5, 2.0], np.float32), stop_gradient=False)
    with paddle.autograd.saved_tensors_hooks(pack, unpack):
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 4.0])
    assert calls["pack"] > 0 and calls["unpack"] > 0


def test_jit_enable_to_static_toggle():
    calls = []

    class M(paddle.nn.Layer):
        def forward(self, x):
            calls.append("py")
            return x * 2

    m = paddle.jit.to_static(M())
    x = paddle.to_tensor(np.ones(2, np.float32))
    paddle.jit.enable_to_static(False)
    try:
        m(x)
        n_eager = len(calls)
        assert n_eager >= 1
    finally:
        paddle.jit.enable_to_static(True)
    paddle.jit.set_code_level(50)
    paddle.jit.set_verbosity(3)


def test_sparse_long_tail():
    from paddle_tpu import sparse

    d = np.array([[0, 2.0], [3.0, 0]], np.float32)
    s = sparse.sparse_coo_tensor(np.array([[0, 1], [1, 0]]),
                                 np.array([2.0, 3.0], np.float32), (2, 2))
    r = sparse.reshape(s, [4])
    np.testing.assert_allclose(r.to_dense().numpy(), d.reshape(4))
    v = sparse.mv(s, paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(v.numpy(), d @ [1.0, 2.0])
    am = sparse.addmm(paddle.to_tensor(np.ones((2, 2), np.float32)), s,
                      paddle.to_tensor(np.eye(2, dtype=np.float32)),
                      beta=0.5, alpha=2.0)
    np.testing.assert_allclose(am.numpy(), 0.5 + 2.0 * d)
    np.testing.assert_allclose(sparse.expm1(s).to_dense().numpy(),
                               np.where(d != 0, np.expm1(d), 0), rtol=1e-6)
    assert sparse.is_same_shape(s, paddle.to_tensor(d))


def test_geometric_reindex_heter():
    from paddle_tpu import geometric

    x = paddle.to_tensor(np.array([10, 20], np.int64))
    nb1 = paddle.to_tensor(np.array([20, 30], np.int64))
    cnt1 = paddle.to_tensor(np.array([1, 1], np.int32))
    nb2 = paddle.to_tensor(np.array([40], np.int64))
    cnt2 = paddle.to_tensor(np.array([1, 0], np.int32))
    src, dst, nodes = geometric.reindex_heter_graph(
        x, [nb1, nb2], [cnt1, cnt2])
    np.testing.assert_array_equal(nodes.numpy(), [10, 20, 30, 40])
    np.testing.assert_array_equal(src.numpy(), [1, 2, 3])
    np.testing.assert_array_equal(dst.numpy(), [0, 1, 0])


def test_saved_tensors_hooks_compose_with_create_graph():
    """Hooks may be installed during recording and higher-order grads stay
    correct (create_graph replays from the live tensors — see the
    saved_tensors_hooks docstring)."""
    def pack(t):
        return np.asarray(t.numpy())

    def unpack(obj):
        return paddle.to_tensor(obj)

    x = paddle.to_tensor(np.array([0.7], np.float32), stop_gradient=False)
    with paddle.autograd.saved_tensors_hooks(pack, unpack):
        y = (x * x * x).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x])
    np.testing.assert_allclose(g2.numpy(), [6 * 0.7], rtol=1e-5)


def test_hfftn_s_maps_to_last_axes():
    rs = np.random.RandomState(1)
    a = rs.randn(3, 4, 6).astype("complex64")
    out = paddle.fft.hfftn(paddle.to_tensor(a), s=(4, 6))
    ref = np.fft.hfft(np.fft.fft(a, n=4, axis=1), n=6, axis=2)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-3)


def test_sparse_reshape_preserves_csr():
    from paddle_tpu import sparse

    d = np.array([[0, 2.0, 0], [3.0, 0, 4.0]], np.float32)
    s = sparse.sparse_csr_tensor(np.array([0, 1, 3]), np.array([1, 0, 2]),
                                 np.array([2.0, 3.0, 4.0], np.float32),
                                 (2, 3))
    r = sparse.reshape(s, [3, 2])
    assert sparse.is_sparse_csr(r)
    np.testing.assert_allclose(r.to_dense().numpy(), d.reshape(3, 2))


def test_weight_norm_param_attr_usable():
    attr = static.WeightNormParamAttr(dim=0)
    lin = paddle.nn.Linear(4, 4, weight_attr=attr)
    assert lin.weight.shape == (4, 4)
    assert attr.dim == 0
