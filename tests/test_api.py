"""The ISSUE-19 front door, fast tier: multi-tenant scheduling policy
units (weighted fair share, priority admission/preemption, shed-vs-defer)
over the REAL Scheduler + BlockKVCache, golden fixtures for the API's
parsing/error/SSE surfaces, and a real-socket ApiServer driven against a
duck-typed fake engine (no jax compiles, no subprocesses) covering
streaming framing, auth, rejection, shed 429, and the no-hang deadline
backstop.  The engine-parity half (streamed tokens == generate()) lives
in the serve_smoke --api leg; the chaos half (stall + mid-stream kill)
in scripts/api_smoke.py (slow tier, run at the bottom of this file).
"""
import itertools
import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.monitor import slo as mslo
from paddle_tpu.monitor import wire
from paddle_tpu.serving import BlockKVCache, Request, SamplingParams, Scheduler
from paddle_tpu.serving.api import ApiServer, api_error, parse_api_keys
from paddle_tpu.serving.scheduler import (PRIORITIES, priority_rank,
                                          should_shed, tenant_weights,
                                          worst_fast_burn)


# -- multi-tenant scheduling policy (real Scheduler, no engine) --------------

def _cache(num_blocks=64):
    return BlockKVCache(num_layers=1, num_blocks=num_blocks, block_size=4,
                        num_heads=1, head_dim=2)


def _req(rid, tenant=None, priority="interactive", prompt_len=4,
         max_new_tokens=4):
    return Request(rid, list(range(1, prompt_len + 1)), SamplingParams(
        max_new_tokens=max_new_tokens, tenant=tenant, priority=priority))


def _drive_saturated(weights, tenants, rounds=400):
    """A tiny engine loop in pure Python: both tenants keep two requests
    queued (saturation), max_num_seqs=1 so every admission is contended,
    each request prefills 4 tokens then decodes to max_new_tokens.
    Returns generated-token counts per tenant."""
    sched = Scheduler(_cache(), max_num_seqs=1, weights=weights)
    counts = {t: 0 for t in tenants}
    nid = itertools.count()
    for _ in range(rounds):
        for t in tenants:   # top up: saturating offered load per tenant
            backlog = sum(1 for r in list(sched.waiting) + sched.running
                          if r.params.tenant == t)
            for _ in range(2 - backlog):
                sched.add(_req(f"{t}-{next(nid)}", tenant=t))
        out = sched.schedule()
        if out.kind == "prefill":
            r = out.prefill_request
            r.num_computed += out.chunk_len
            if r.prefill_done:   # the engine samples token 1 off prefill
                r.record_token(7)
        elif out.kind == "decode":
            for r in out.decode_requests:
                r.record_token(7)
        for r in sched.retire_finished():
            counts[r.params.tenant] += len(r.output_ids)
    return counts


class TestFairShare:
    def test_weighted_split_within_10_percent(self):
        # two saturating tenants at weights 3:1 -> served tokens split
        # 3:1 (the ISSUE-19 acceptance bound: within 10%)
        counts = _drive_saturated({"acme": 3.0, "free": 1.0},
                                  ("acme", "free"))
        assert counts["free"] > 0, counts
        ratio = counts["acme"] / counts["free"]
        assert abs(ratio - 3.0) / 3.0 <= 0.10, counts

    def test_equal_weights_split_evenly(self):
        counts = _drive_saturated({}, ("a", "b"))   # unlisted = weight 1
        assert counts["b"] > 0, counts
        ratio = counts["a"] / counts["b"]
        assert abs(ratio - 1.0) <= 0.10, counts

    def test_default_params_degenerate_to_fifo(self):
        # no tenants, one priority: admission must be exact arrival order
        sched = Scheduler(_cache(), max_num_seqs=4)
        for i in range(3):
            sched.add(_req(f"r{i}"))
        admitted = []
        for _ in range(3):
            out = sched.schedule()
            assert out.kind == "prefill"
            out.prefill_request.num_computed = out.prefill_request.prompt_len
            admitted.append(out.prefill_request.req_id)
        assert admitted == ["r0", "r1", "r2"]

    def test_late_joiner_starts_at_current_minimum(self):
        # a tenant arriving after incumbents built up service history
        # must NOT monopolize admission until it "catches up" from zero
        sched = Scheduler(_cache(), max_num_seqs=1, weights={})
        sched.tenant_served = {"a": 40.0, "b": 50.0}
        assert sched._served_of("newcomer") == 40.0
        sched._charge(_req("n1", tenant="newcomer"), 4)
        assert sched.tenant_served["newcomer"] == 44.0


class TestPriority:
    def test_admission_prefers_higher_class_over_arrival(self):
        # best-effort arrived FIRST; interactive must still go first —
        # then fair share/arrival break ties within a class
        sched = Scheduler(_cache(), max_num_seqs=4)
        sched.add(_req("be", priority="best-effort"))
        sched.add(_req("batch", priority="batch"))
        sched.add(_req("int", priority="interactive"))
        order = []
        for _ in range(3):
            out = sched.schedule()
            assert out.kind == "prefill"
            out.prefill_request.num_computed = out.prefill_request.prompt_len
            order.append(out.prefill_request.req_id)
        assert order == ["int", "batch", "be"]

    def test_preemption_victimizes_lowest_priority_youngest(self):
        sched = Scheduler(_cache(), max_num_seqs=4)
        rows = [_req("int-old", priority="interactive"),
                _req("be-old", priority="best-effort"),
                _req("be-young", priority="best-effort"),
                _req("batch", priority="batch")]
        for i, r in enumerate(rows):
            r.arrival = i
            r.state = Request.RUNNING
        sched.running = list(rows)
        assert sched._pick_victim().req_id == "be-young"
        assert sched._pick_victim(exclude=rows[2]).req_id == "be-old"
        # one class in play: the original youngest-arrival pick
        sched.running = [rows[0], _req("int-young")]
        sched.running[1].arrival = 9
        assert sched._pick_victim().req_id == "int-young"

    def test_unknown_priority_ranks_worst(self):
        assert priority_rank("interactive") == 0
        assert priority_rank("batch") == 1
        assert priority_rank("best-effort") == len(PRIORITIES) - 1
        assert priority_rank("totally-bogus") == priority_rank("best-effort")
        assert priority_rank(None) == priority_rank("best-effort")


class TestShedPolicy:
    @pytest.mark.parametrize("priority,burn,expect", [
        ("interactive", 10.0, False),    # never shed: defers in queue
        ("batch", 10.0, False),          # never shed: defers in queue
        ("best-effort", 10.0, True),     # burn >= threshold: shed
        ("best-effort", 1.9, False),     # below the 2.0 default: defer
        ("best-effort", 2.0, True),      # threshold is inclusive
        ("bogus", 10.0, True),           # unknown class degrades to BE
        (None, 10.0, True),
    ])
    def test_shed_vs_defer_matrix(self, priority, burn, expect):
        assert should_shed(priority, burn=burn) is expect

    def test_threshold_env_override(self, monkeypatch):
        monkeypatch.setenv("PTPU_SHED_BURN", "5.0")
        assert not should_shed("best-effort", burn=4.9)
        assert should_shed("best-effort", burn=5.0)
        monkeypatch.setenv("PTPU_SHED_BURN", "not-a-number")
        assert should_shed("best-effort", burn=2.0)   # falls back to 2.0

    def test_worst_fast_burn_reads_report(self):
        rep = {"enabled": True, "objectives": [
            {"burn_rate": {"fast": 1.5, "slow": 0.2}},
            {"burn_rate": {"fast": 3.25, "slow": 0.1}},
        ]}
        assert worst_fast_burn(rep) == 3.25
        assert worst_fast_burn({"enabled": False, "objectives": []}) == 0.0
        assert worst_fast_burn({}) == 0.0

    def test_tenant_weights_parsing(self):
        assert tenant_weights("acme:3,free:1") == {"acme": 3.0, "free": 1.0}
        assert tenant_weights("solo") == {"solo": 1.0}
        # malformed / non-positive entries are dropped, never fatal
        assert tenant_weights("bad:x, ok:2 ,:3,neg:-1,zero:0") == {"ok": 2.0}
        assert tenant_weights("") == {}


# -- API parsing / error-shape golden fixtures -------------------------------

class TestApiFixtures:
    def test_parse_api_keys(self):
        assert parse_api_keys(
            "sk-a:acme:interactive,sk-b:free:best-effort") == {
                "sk-a": ("acme", "interactive"),
                "sk-b": ("free", "best-effort")}
        assert parse_api_keys("sk-a") == {"sk-a": (None, None)}
        assert parse_api_keys("sk-a:t") == {"sk-a": ("t", None)}
        assert parse_api_keys(" sk-a:t:p , ,:orphan") == {
            "sk-a": ("t", "p")}
        assert parse_api_keys("") == {}

    def test_api_error_matches_wire_schema(self):
        doc = api_error("boom", code="shed", param="prompt")
        assert set(doc) == {"error"}
        assert tuple(doc["error"].keys()) == wire.API_ERROR_KEYS
        assert doc["error"]["message"] == "boom"
        assert doc["error"]["code"] == "shed"
        assert api_error("x")["error"]["type"] == "invalid_request_error"

    def test_shed_and_rejected_are_slo_good(self):
        from paddle_tpu.monitor.slo import _GOOD_REASONS

        assert "shed" in _GOOD_REASONS and "rejected" in _GOOD_REASONS
        # and the reqlog wire schema carries the tenant dimension
        assert "tenant" in wire.REQLOG_EVENT_KEYS
        assert "priority" in wire.REQLOG_EVENT_KEYS


# -- the HTTP tier over a duck-typed fake engine -----------------------------

class _FakeReq:
    def __init__(self, prompt_ids, params):
        self.prompt_ids = list(prompt_ids)
        self.params = params
        self.output_ids = []
        self.finish_reason = None


class _FakeEngine:
    """The LLMEngine half the pump drives, deterministic and compile-free:
    one token per step (last prompt id + position), finishing at
    max_new_tokens/eos.  `wedged=True` never produces tokens — the
    backstop-timer case."""

    def __init__(self, wedged=False):
        self._requests = {}
        self._next = itertools.count()
        self.released = []
        self.wedged = wedged

    def add_request(self, prompt_ids, params):
        if not prompt_ids:
            raise ValueError("empty prompt")
        rid = next(self._next)
        self._requests[rid] = _FakeReq(prompt_ids, params)
        return rid

    def has_unfinished(self):
        return any(r.finish_reason is None for r in self._requests.values())

    def step(self):
        if self.wedged:
            time.sleep(0.005)
            return
        for r in self._requests.values():
            if r.finish_reason is not None:
                continue
            tok = (r.prompt_ids[-1] + len(r.output_ids) + 1) % 50000
            r.output_ids.append(tok)
            p = r.params
            if len(r.output_ids) >= p.max_new_tokens or (
                    p.eos_token_id is not None and tok == p.eos_token_id):
                r.finish_reason = "stop"

    def release_request(self, rid, reason=None):
        self.released.append((rid, reason))
        self._requests.pop(rid, None)


class _BurnStub:
    """Duck-typed monitor.slo engine: the full contract the serving stack
    touches is report() + violates() + tick()."""

    def __init__(self, fast):
        self.fast = fast

    def report(self):
        return {"enabled": True, "objectives": [
            {"objective": "stub", "burn_rate": {"fast": self.fast,
                                                "slow": 0.0}}]}

    def violates(self, **kw):
        return False

    def tick(self, now=None):
        return None


def _post(url, body, key=None, timeout=30):
    headers = {"Content-Type": "application/json"}
    if key:
        headers["Authorization"] = "Bearer " + key
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers=headers)
    return urllib.request.urlopen(req, timeout=timeout)


def _sse_chunks(resp):
    """Parse a full SSE body into its JSON chunks, asserting the exact
    framing: every event is one `data: <json>` line + blank line, and
    the terminator is `data: [DONE]`."""
    raw = resp.read().decode("utf-8")
    events = [e for e in raw.split("\n\n") if e]
    assert all(e.startswith("data: ") for e in events), raw
    assert events[-1] == "data: [DONE]", raw
    return [json.loads(e[len("data: "):]) for e in events[:-1]]


@pytest.fixture()
def server():
    eng = _FakeEngine()
    srv = ApiServer(engine=eng, api_keys={}, poll_s=0.005)
    try:
        yield srv, eng
    finally:
        srv.stop()


class TestApiServer:
    def test_models_endpoint(self, server):
        srv, _ = server
        doc = json.loads(urllib.request.urlopen(
            srv.url + "/v1/models", timeout=10).read())
        assert doc["data"][0]["id"] == "paddle-tpu"

    def test_completion_json(self, server):
        srv, _ = server
        doc = json.loads(_post(srv.url + "/v1/completions",
                               {"prompt": [5, 6, 7],
                                "max_tokens": 4}).read())
        assert doc["object"] == "text_completion"
        ch = doc["choices"][0]
        assert ch["token_ids"] == [8, 9, 10, 11]   # fake's arithmetic
        assert ch["finish_reason"] == "stop"
        assert ch["text"] == " 8 9 10 11"          # default decode
        assert doc["usage"] == {"prompt_tokens": 3, "completion_tokens": 4,
                                "total_tokens": 7}

    def test_completion_stream_framing(self, server):
        srv, eng = server
        chunks = _sse_chunks(_post(srv.url + "/v1/completions",
                                   {"prompt": [5, 6, 7], "max_tokens": 4,
                                    "stream": True}))
        # one chunk per pump cycle (= one fake token) + the final chunk
        toks = [t for c in chunks for t in c["choices"][0]["token_ids"]]
        assert toks == [8, 9, 10, 11], chunks
        assert all(c["object"] == "text_completion" for c in chunks)
        assert len({c["id"] for c in chunks}) == 1   # stable stream id
        reasons = [c["choices"][0]["finish_reason"] for c in chunks]
        assert reasons[-1] == "stop"
        assert all(r is None for r in reasons[:-1]), reasons
        assert chunks[-1]["choices"][0]["token_ids"] == []
        assert not eng._requests, "stream end must release the request"

    def test_chat_completion_json_and_stream(self, server):
        srv, _ = server
        body = {"messages": [{"role": "user", "content": [5, 6, 7]}],
                "max_tokens": 3}
        doc = json.loads(_post(srv.url + "/v1/chat/completions",
                               body).read())
        assert doc["object"] == "chat.completion"
        msg = doc["choices"][0]["message"]
        assert msg["role"] == "assistant" and msg["content"] == " 8 9 10"
        chunks = _sse_chunks(_post(srv.url + "/v1/chat/completions",
                                   dict(body, stream=True)))
        assert chunks[0]["object"] == "chat.completion.chunk"
        assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
        toks = [t for c in chunks for t in c["choices"][0]["token_ids"]]
        assert toks == [8, 9, 10]

    def test_eos_stops_early(self, server):
        srv, _ = server
        doc = json.loads(_post(srv.url + "/v1/completions",
                               {"prompt": [5, 6, 7], "max_tokens": 16,
                                "eos_token_id": 9}).read())
        assert doc["choices"][0]["token_ids"] == [8, 9]

    def test_bad_requests_are_400_with_wire_shape(self, server):
        srv, _ = server
        for body in ({"prompt": "strings need a tokenizer"},
                     {"prompt": []}, {"prompt": {"not": "a list"}},
                     {"messages": []}):
            path = ("/v1/chat/completions" if "messages" in body
                    else "/v1/completions")
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(srv.url + path, body)
            assert ei.value.code == 400
            err = json.loads(ei.value.read())["error"]
            assert tuple(err.keys()) == wire.API_ERROR_KEYS

    def test_unknown_model_404(self, server):
        srv, _ = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/v1/completions",
                  {"model": "gpt-oss-999", "prompt": [1]})
        assert ei.value.code == 404
        assert json.loads(ei.value.read())["error"]["code"] == \
            "model_not_found"

    def test_auth_401_and_tenant_mapping(self):
        eng = _FakeEngine()
        srv = ApiServer(engine=eng, poll_s=0.005,
                        api_keys={"sk-a": ("acme", "batch")})
        try:
            for key in (None, "sk-wrong"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(srv.url + "/v1/completions", {"prompt": [1]},
                          key=key)
                assert ei.value.code == 401
                err = json.loads(ei.value.read())["error"]
                assert err["type"] == "authentication_error"
                assert err["code"] == "invalid_api_key"
            _post(srv.url + "/v1/completions",
                  {"prompt": [1], "max_tokens": 1}, key="sk-a").read()
            (rid, reason), = eng.released
            assert reason is None   # finished normally, key accepted
            # the key's (tenant, priority) landed on SamplingParams; the
            # body can override priority but not the key's tenant
            st = _post(srv.url + "/v1/completions",
                       {"prompt": [1], "max_tokens": 1, "user": "spoof",
                        "priority": "interactive"}, key="sk-a")
            st.read()
        finally:
            srv.stop()

    def test_shed_429_via_slo_stub(self):
        eng = _FakeEngine()
        srv = ApiServer(engine=eng, poll_s=0.005,
                        api_keys={"sk-be": ("free", "best-effort"),
                                  "sk-int": ("acme", "interactive")})
        mslo.install(_BurnStub(fast=10.0))
        try:
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(srv.url + "/v1/completions",
                      {"prompt": [1], "max_tokens": 1}, key="sk-be")
            bounded = time.monotonic() - t0
            assert ei.value.code == 429
            assert ei.value.headers.get("Retry-After")
            assert json.loads(ei.value.read())["error"]["code"] == "shed"
            assert bounded < 5.0, "shed must answer immediately"
            assert not eng._requests, "shed work must never reach the queue"
            # interactive under the SAME burn: served, not shed
            doc = json.loads(_post(srv.url + "/v1/completions",
                                   {"prompt": [1], "max_tokens": 1},
                                   key="sk-int").read())
            assert doc["choices"][0]["finish_reason"] == "stop"
            # burn below threshold: best-effort is served again
            mslo.install(_BurnStub(fast=0.5))
            doc = json.loads(_post(srv.url + "/v1/completions",
                                   {"prompt": [1], "max_tokens": 1},
                                   key="sk-be").read())
            assert doc["choices"][0]["finish_reason"] == "stop"
        finally:
            mslo.refresh()
            srv.stop()

    def test_deadline_backstop_never_hangs(self, monkeypatch):
        # a wedged backend (steps but never produces): the HTTP tier's
        # deadline+grace budget must answer 504, bounded, both modes
        from paddle_tpu.serving import api as api_mod

        monkeypatch.setattr(api_mod, "_DEADLINE_GRACE_S", 0.3)
        eng = _FakeEngine(wedged=True)
        srv = ApiServer(engine=eng, api_keys={}, poll_s=0.005)
        try:
            for stream in (False, True):
                t0 = time.monotonic()
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(srv.url + "/v1/completions",
                          {"prompt": [1], "max_tokens": 4,
                           "deadline_s": 0.2, "stream": stream})
                dt = time.monotonic() - t0
                assert ei.value.code == 504
                assert json.loads(ei.value.read())["error"]["code"] == \
                    "deadline"
                assert dt < 3.0, f"stream={stream} hung {dt:.1f}s"
            # the pump releases cancelled requests on its next cycle
            deadline = time.monotonic() + 5.0
            while eng._requests and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not eng._requests, "timed-out requests must be released"
        finally:
            srv.stop()

    def test_backend_exception_surfaces_as_500(self, server):
        srv, eng = server

        def boom():
            raise RuntimeError("backend on fire")

        eng.step = boom
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/v1/completions",
                  {"prompt": [1], "max_tokens": 2})
        assert ei.value.code == 500
        err = json.loads(ei.value.read())["error"]
        assert err["type"] == "api_error"
        assert "backend on fire" in err["message"]


# -- the chaos half: scripts/api_smoke.py (slow tier) ------------------------

@pytest.mark.slow
def test_api_smoke_script():
    """Stall + mid-stream SIGKILL behind the API: every HTTP stream
    completes, errors cleanly, or fails over — never hangs."""
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "scripts" / "api_smoke.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS", "PTPU_FAULTS")}
    env["PTPU_FORCE_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PTPU_MONITOR"] = "1"
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=560)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "API SMOKE OK" in proc.stdout
    assert "stall leg:" in proc.stdout
    assert "failover leg:" in proc.stdout
