"""Multi-process data parallelism over jax.distributed (the multi-host
DCN bring-up path; reference analog: unittests/test_dist_base.py —
subprocess trainers on localhost endpoints asserting loss parity vs the
single-process run). Two processes, one CPU device each, rendezvous via
PADDLE_MASTER, train the same global batch; losses and weights must
match bit-for-bit across ranks AND the single-process baseline.

Also guards the import contract this path depends on: `import
paddle_tpu` must not initialize the XLA backend
(jax.distributed.initialize must come first on multi-host).
"""
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_WORKERS = pathlib.Path(__file__).resolve().parent / "workers"
_WORKER = _WORKERS / "multiproc_dp_worker.py"
_HYBRID_WORKER = _WORKERS / "multiproc_hybrid_worker.py"
_SP_WORKER = _WORKERS / "multiproc_sp_worker.py"
_PP_WORKER = _WORKERS / "multiproc_pp_worker.py"


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(nproc, worker=None):
    port = _free_port()
    procs = []
    for rank in range(nproc):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        env.update(PADDLE_MASTER=f"127.0.0.1:{port}",
                   PADDLE_TRAINERS_NUM=str(nproc),
                   PADDLE_TRAINER_ID=str(rank),
                   PTPU_FORCE_PLATFORM="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker or _WORKER)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            # shorter than jax.distributed's ~300s init timeout so a
            # crashed sibling surfaces HERE, with every worker's output
            out, _ = p.communicate(timeout=200)
            outs.append(out)
    finally:
        for p in procs:          # never leave a rank holding the port
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, "\n---\n".join(o[-1500:] for o in outs)
    return outs


def _parse_losses(out):
    for line in out.splitlines():
        if line.startswith("LOSSES"):
            return [float(v) for v in line.split()[1:]]
    raise AssertionError(out[-1500:])


def _parse(out):
    losses = _parse_losses(out)
    wsum = None
    for line in out.splitlines():
        if line.startswith("WSUM"):
            wsum = float(line.split()[1])
    assert wsum is not None, out[-1500:]
    return losses, wsum


def test_two_process_dp_parity():
    two = [_parse(o) for o in _run_workers(2)]
    one = _parse(_run_workers(1)[0])

    # both ranks observed the identical training trajectory
    assert two[0] == two[1]
    # and it matches the single-process baseline (loss parity, the
    # reference's TestDistBase acceptance criterion)
    for a, b in zip(two[0][0], one[0]):
        assert abs(a - b) < 1e-6, (two[0][0], one[0])
    assert abs(two[0][1] - one[1]) < 1e-6


def test_import_does_not_init_backend():
    code = (
        "import os;"
        "os.environ['PTPU_FORCE_PLATFORM']='cpu';"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import jax._src.xla_bridge as xb;"
        "hits=[];orig=xb.backends;"
        "xb.backends=lambda: (hits.append(1), orig())[1];"
        "import paddle_tpu;"
        "assert not hits, 'import paddle_tpu initialized the XLA backend';"
        "print('IMPORT_CLEAN')"
    )
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PTPU_FORCE_PLATFORM"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=180,
                          cwd=str(_WORKER.parent.parent.parent))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "IMPORT_CLEAN" in proc.stdout


def test_two_process_hybrid_gpt():
    """dp across the process boundary x mp=4 inside each process: the
    multi-host hybrid topology. Loss trajectory must match (to collective
    reduction-order noise) the same dp2xmp4 mesh on 8 single-process
    devices — covered by tests/test_models.py parity suites."""
    ranks = [_parse_losses(o) for o in _run_workers(2, worker=_HYBRID_WORKER)]
    assert ranks[0] == ranks[1]
    # monotone improvement on 3 steps of the tiny GPT
    assert ranks[0][-1] < ranks[0][0]
    # single-process baseline through the SAME runner (init_parallel_env
    # skips jax.distributed at nproc=1): the worker pins 4 local devices,
    # so this is dp1xmp4 — parity across a DIFFERENT dp split of the same
    # global batch is the stronger check
    base = _parse_losses(_run_workers(1, worker=_HYBRID_WORKER)[0])
    for a, b in zip(ranks[0], base):
        assert abs(a - b) < 1e-5, (ranks[0], base)


def test_two_process_ring_sp():
    """The zigzag sp ring crossing the process boundary (ppermute over
    the inter-process link): both ranks agree, the trajectory improves,
    and it matches the sp4 single-process run of the same global batch
    to collective reduction noise."""
    os.environ["CP_LAYOUT"] = "zigzag"
    try:
        ranks = [_parse_losses(o)
                 for o in _run_workers(2, worker=_SP_WORKER)]
        base = _parse_losses(_run_workers(1, worker=_SP_WORKER)[0])
    finally:
        os.environ.pop("CP_LAYOUT", None)
    assert ranks[0] == ranks[1]
    assert ranks[0][-1] < ranks[0][0]
    for a, b in zip(ranks[0], base):
        assert abs(a - b) < 1e-5, (ranks[0], base)


def test_two_process_pipeline():
    """pp spanning the process boundary (pp2 x mp4 over 2 procs): both
    ranks read the SAME replicated loss, and the trajectory matches the
    single-process pp2 x mp2 run of the same global batch — parity
    across both the process split and a different mp width."""
    ranks = [_parse_losses(o) for o in _run_workers(2, worker=_PP_WORKER)]
    base = _parse_losses(_run_workers(1, worker=_PP_WORKER)[0])
    assert ranks[0] == ranks[1]
    assert ranks[0][-1] < ranks[0][0]
    for a, b in zip(ranks[0], base):
        assert abs(a - b) < 1e-5, (ranks[0], base)
