"""Pipeline-parallel tests (reference: hybrid_parallel_pp_* parity suites —
pipelined run must match the serial single-rank run)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.parallel.pipeline import pipeline_apply, scan_blocks
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_test_config

import pytest

pytestmark = pytest.mark.slow


def _block(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def test_pipeline_matches_serial_fwd_and_grad():
    parallel.init_mesh(dp=2, pp=4)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(0)
    L, H, B = 8, 16, 8
    params = {
        "w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3,
        "b": jnp.asarray(rng.randn(L, H), jnp.float32) * 0.1,
    }
    x = jnp.asarray(rng.randn(B, H), jnp.float32)

    ref = x
    for i in range(L):
        ref = _block({"w": params["w"][i], "b": params["b"][i]}, ref)

    sharded = {
        "w": jax.device_put(params["w"], NamedSharding(mesh, P("pp"))),
        "b": jax.device_put(params["b"], NamedSharding(mesh, P("pp"))),
    }
    xd = jax.device_put(x, NamedSharding(mesh, P("dp")))
    out = jax.jit(lambda p, a: pipeline_apply(_block, p, a, n_microbatches=4))(sharded, xd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def loss_pipe(p, a):
        return jnp.sum(pipeline_apply(_block, p, a, n_microbatches=4) ** 2)

    def loss_ser(p, a):
        return jnp.sum(scan_blocks(_block, p, a) ** 2)

    g1 = jax.jit(jax.grad(loss_pipe))(sharded, xd)
    g2 = jax.jit(jax.grad(loss_ser))(params, x)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]), atol=1e-4)


def test_interleaved_pipeline_matches_serial():
    """num_chunks>1 virtual-stage schedule: forward and grads must match
    the serial stack (reference PipelineParallelWithInterleave parity)."""
    parallel.init_mesh(pp=2)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(5)
    L, H, B, M, V = 8, 16, 8, 4, 2
    params = {
        "w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3,
        "b": jnp.asarray(rng.randn(L, H), jnp.float32) * 0.1,
    }
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
               for k, v in params.items()}

    out = jax.jit(lambda p, a: pipeline_apply(
        _block, p, a, n_microbatches=M, num_chunks=V))(sharded, x)
    ref = x
    for i in range(L):
        ref = _block({"w": params["w"][i], "b": params["b"][i]}, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def loss_int(p, a):
        return jnp.sum(pipeline_apply(_block, p, a, n_microbatches=M,
                                      num_chunks=V) ** 2)

    def loss_ser(p, a):
        return jnp.sum(scan_blocks(_block, p, a) ** 2)

    g1 = jax.jit(jax.grad(loss_int))(sharded, x)
    g2 = jax.jit(jax.grad(loss_ser))(params, x)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   atol=1e-4)


def test_interleaved_validates_divisibility():
    parallel.init_mesh(pp=2)
    params = {"w": jnp.zeros((8, 4, 4)), "b": jnp.zeros((8, 4))}
    x = jnp.zeros((6, 4))
    with pytest.raises(ValueError, match="divisible by"):
        # M=3 not divisible by pp=2
        pipeline_apply(_block, params, x, n_microbatches=3, num_chunks=2)
    with pytest.raises(ValueError, match="pp\\*num_chunks"):
        pipeline_apply(_block, params, x, n_microbatches=2, num_chunks=3)


def _stacked_losses(mesh_kwargs, steps=5, schedule="gpipe", chunks=1):
    paddle.seed(42)
    parallel.init_mesh(**mesh_kwargs)
    cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True,
                          pp_schedule=schedule, pp_num_chunks=chunks,
                          pp_num_microbatches=2 if chunks > 1 else 0)
    model = parallel.place_model(GPTForCausalLM(cfg))
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        if schedule == "1f1b":
            loss = model.pretrain_loss(x, y)
        else:
            loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype("int32"))
    return [float(compiled(ids, lab)) for _ in range(steps)]


def _toy_loss(tail, h, y):
    # tail-owned head: project then squared error against labels
    out = h @ tail["head"]
    return jnp.mean((out - y) ** 2)


def test_1f1b_matches_serial_loss_and_grads():
    from paddle_tpu.parallel.pipeline import pipeline_1f1b

    parallel.init_mesh(pp=4)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(1)
    L, H, B, M = 8, 16, 8, 4
    params = {
        "w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3,
        "b": jnp.asarray(rng.randn(L, H), jnp.float32) * 0.1,
    }
    tail = {"head": jnp.asarray(rng.randn(H, 4), jnp.float32) * 0.3}
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    y = jnp.asarray(rng.randn(B, 4), jnp.float32)

    def loss_pipe(p, tl, a):
        return pipeline_1f1b(_block, _toy_loss, p, tl, a, y, n_microbatches=M)

    def loss_ser(p, tl, a):
        # serial reference: mean over the same micro-batch split
        losses = []
        for m in range(M):
            am, ym = a[m * B // M:(m + 1) * B // M], y[m * B // M:(m + 1) * B // M]
            losses.append(_toy_loss(tl, scan_blocks(_block, p, am), ym))
        return jnp.mean(jnp.stack(losses))

    sharded = {
        "w": jax.device_put(params["w"], NamedSharding(mesh, P("pp"))),
        "b": jax.device_put(params["b"], NamedSharding(mesh, P("pp"))),
    }
    l1 = jax.jit(loss_pipe)(sharded, tail, x)
    l2 = jax.jit(loss_ser)(params, tail, x)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    g1 = jax.jit(jax.grad(loss_pipe, argnums=(0, 1, 2)))(sharded, tail, x)
    g2 = jax.jit(jax.grad(loss_ser, argnums=(0, 1, 2)))(params, tail, x)
    for t1, t2 in zip(jax.tree_util.tree_leaves(g1),
                      jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(t1), np.asarray(t2),
                                   rtol=1e-4, atol=1e-5)


def test_1f1b_bf16_grads_keep_dtype():
    """bf16 params/activations: grads must come back bf16 (the bench's
    precision recipe) — guards the custom_vjp cotangent dtype contract."""
    from paddle_tpu.parallel.pipeline import pipeline_1f1b

    parallel.init_mesh(pp=2)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(3)
    L, H, B = 4, 16, 4
    params = {"w": jnp.asarray(rng.randn(L, H, H), jnp.bfloat16) * 0.3,
              "b": jnp.zeros((L, H), jnp.bfloat16)}
    tail = {"head": jnp.asarray(rng.randn(H, 4), jnp.bfloat16)}
    x = jnp.asarray(rng.randn(B, H), jnp.bfloat16)
    y = jnp.asarray(rng.randn(B, 4), jnp.float32)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
               for k, v in params.items()}

    def f(p, tl, a):
        return pipeline_1f1b(_block, _toy_loss, p, tl, a, y,
                             n_microbatches=2)

    gp, gt, gx = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(sharded, tail, x)
    assert gp["w"].dtype == jnp.bfloat16
    assert gt["head"].dtype == jnp.bfloat16
    assert gx.dtype == jnp.bfloat16
    assert float(jnp.sum(jnp.abs(gp["w"].astype(jnp.float32)))) > 0


def test_1f1b_bounds_activation_memory():
    """The 1F1B schedule's compiled temp footprint must not grow with M
    (GPipe's does — that is the entire point of the schedule)."""
    from paddle_tpu.parallel.pipeline import pipeline_1f1b

    parallel.init_mesh(pp=4)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(2)
    L, H, B = 4, 64, 64
    params = {"w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.1,
              "b": jnp.zeros((L, H), jnp.float32)}
    tail = {"head": jnp.asarray(rng.randn(H, 4), jnp.float32)}
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    y = jnp.asarray(rng.randn(B, 4), jnp.float32)
    sharded = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
               for k, v in params.items()}

    def temp_bytes(M):
        def f(p, tl, a):
            return pipeline_1f1b(_block, _toy_loss, p, tl, a, y,
                                 n_microbatches=M)
        lowered = jax.jit(jax.grad(f, argnums=(0, 1, 2))).lower(
            sharded, tail, x)
        ma = lowered.compile().memory_analysis()
        if ma is None:  # backend without memory analysis: vacuous pass
            return None
        return ma.temp_size_in_bytes

    t4, t16 = temp_bytes(4), temp_bytes(16)
    if t4 is not None and t16 is not None and t4 > 0:
        # stash ring depth stays pp regardless of M; allow slack for
        # per-microbatch bookkeeping buffers (dxs is O(B) total, fixed).
        assert t16 <= t4 * 1.5, (t4, t16)


def test_gpt_3d_parallel_parity():
    """dp2 x pp2 x mp2 pipelined GPT matches the single-device loss curve."""
    base = _stacked_losses(dict())
    hybrid = _stacked_losses(dict(dp=2, pp=2, mp=2))
    np.testing.assert_allclose(base, hybrid, rtol=2e-2, atol=2e-3)


def test_gpt_interleaved_schedule_parity():
    """pp=2 with 2 virtual chunks per stage matches the single-device
    loss curve through full training steps."""
    base = _stacked_losses(dict())
    inter = _stacked_losses(dict(pp=2), chunks=2)
    np.testing.assert_allclose(base, inter, rtol=2e-2, atol=2e-3)


def test_gpt_1f1b_schedule_parity():
    """pretrain_loss under pp=2 1F1B matches the single-device loss curve
    (reference hybrid_parallel_pp_alexnet-style schedule parity)."""
    base = _stacked_losses(dict())
    f1b = _stacked_losses(dict(pp=2), schedule="1f1b")
    np.testing.assert_allclose(base, f1b, rtol=2e-2, atol=2e-3)


def test_gpt_1f1b_loss_mask_global_mean():
    """With a loss_mask whose live-token counts differ per micro-batch, the
    1F1B loss must equal the criterion's GLOBAL sum(loss*mask)/sum(mask) —
    not a mean of per-micro-batch means."""
    paddle.seed(7)
    parallel.init_mesh(pp=2)
    cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True,
                          pp_schedule="1f1b", pp_num_microbatches=4)
    model = parallel.place_model(GPTForCausalLM(cfg))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
    # wildly uneven counts: first micro-batch rows nearly all live, last
    # nearly all masked
    mask_np = (rng.rand(8, 16) < np.linspace(0.95, 0.1, 8)[:, None]
               ).astype("float32")
    mask_np[0, 0] = 1.0  # at least one live token
    mask = paddle.to_tensor(mask_np)

    f1b = float(model.pretrain_loss(ids, lab, loss_mask=mask))
    crit = GPTPretrainingCriterion(cfg)
    ref = float(crit(model(ids), lab, mask))
    np.testing.assert_allclose(f1b, ref, rtol=1e-4)


def test_partial_manual_bf16_psum():
    """Tracking test for an XLA-CPU bug: psum of bf16 inside a
    PARTIAL-manual shard_map region (axis_names a strict subset of the
    mesh axes) used to die fatally with `Invalid binary instruction
    opcode copy` in the CPU float-normalization pass. The pipeline
    broadcasts its outputs with exactly that construct, so bf16 pipeline
    models crashed on the CPU test mesh; _psum_safe upcasts the reduce to
    f32 on CPU. This exercises the bf16 pipeline end to end."""
    import jax.numpy as jnp
    from paddle_tpu import parallel
    from paddle_tpu.parallel.pipeline import pipeline_apply

    parallel.init_mesh(pp=2)
    L, H = 4, 32
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(L, H, H) * 0.1, jnp.bfloat16)}

    def block(p, h):
        return jnp.tanh(h @ p["w"])

    x = jnp.asarray(rng.randn(4, 8, H), jnp.bfloat16)
    out = jax.jit(lambda a, p: pipeline_apply(block, p, a,
                                              n_microbatches=2))(x, params)

    # oracle: plain sequential blocks, no pipeline
    ref = x
    for l in range(L):
        ref = jnp.tanh(ref @ params["w"][l])
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_stacked_recompute_parity():
    """cfg.recompute (jax.checkpoint around each stacked block) must not
    change the training step's loss or gradients."""
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer

    losses = {}
    for rc in (False, True):
        parallel.init_mesh(pp=2)
        cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True,
                              recompute=rc)
        paddle.seed(11)
        model = parallel.place_model(GPTForCausalLM(cfg))
        crit = GPTPretrainingCriterion(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def step(x, y):
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = jit.compile(step, models=[model], optimizers=[opt])
        rng = np.random.RandomState(4)
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (4, 16)).astype("int32"))
        lab = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (4, 16)).astype("int32"))
        losses[rc] = [float(compiled(ids, lab)) for _ in range(2)]
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=1e-5, atol=1e-6)
