"""Pipeline-parallel tests (reference: hybrid_parallel_pp_* parity suites —
pipelined run must match the serial single-rank run)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.parallel.pipeline import pipeline_apply, scan_blocks
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_test_config


def _block(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def test_pipeline_matches_serial_fwd_and_grad():
    parallel.init_mesh(dp=2, pp=4)
    mesh = parallel.get_mesh()
    rng = np.random.RandomState(0)
    L, H, B = 8, 16, 8
    params = {
        "w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3,
        "b": jnp.asarray(rng.randn(L, H), jnp.float32) * 0.1,
    }
    x = jnp.asarray(rng.randn(B, H), jnp.float32)

    ref = x
    for i in range(L):
        ref = _block({"w": params["w"][i], "b": params["b"][i]}, ref)

    sharded = {
        "w": jax.device_put(params["w"], NamedSharding(mesh, P("pp"))),
        "b": jax.device_put(params["b"], NamedSharding(mesh, P("pp"))),
    }
    xd = jax.device_put(x, NamedSharding(mesh, P("dp")))
    out = jax.jit(lambda p, a: pipeline_apply(_block, p, a, n_microbatches=4))(sharded, xd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def loss_pipe(p, a):
        return jnp.sum(pipeline_apply(_block, p, a, n_microbatches=4) ** 2)

    def loss_ser(p, a):
        return jnp.sum(scan_blocks(_block, p, a) ** 2)

    g1 = jax.jit(jax.grad(loss_pipe))(sharded, xd)
    g2 = jax.jit(jax.grad(loss_ser))(params, x)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]), atol=1e-4)


def _stacked_losses(mesh_kwargs, steps=5):
    paddle.seed(42)
    parallel.init_mesh(**mesh_kwargs)
    cfg = gpt_test_config(num_hidden_layers=4, stacked_blocks=True)
    model = parallel.place_model(GPTForCausalLM(cfg))
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype("int32"))
    return [float(compiled(ids, lab)) for _ in range(steps)]


def test_gpt_3d_parallel_parity():
    """dp2 x pp2 x mp2 pipelined GPT matches the single-device loss curve."""
    base = _stacked_losses(dict())
    hybrid = _stacked_losses(dict(dp=2, pp=2, mp=2))
    np.testing.assert_allclose(base, hybrid, rtol=2e-2, atol=2e-3)
