"""Ring attention (context parallelism over 'sp') — parity vs full-sequence
attention. Fills the reference's long-context capability gap (SURVEY §5.7)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import parallel
from paddle_tpu.parallel.ring import ring_attention_arrays
from paddle_tpu.ops.pallas_ops import mha_reference


@pytest.fixture
def sp_mesh():
    parallel.init_mesh(dp=2, sp=4)
    yield
    parallel.init_mesh(dp=1)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(sp_mesh, causal):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    ref = mha_reference(q, k, v, None, causal)
    got = jax.jit(lambda q, k, v: ring_attention_arrays(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_grads_match(sp_mesh):
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, None, True) ** 2).sum()

    def loss_ring(q, k, v):
        return (ring_attention_arrays(q, k, v, True) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("stacked", [False, True])
def test_gpt_context_parallel_training_parity(sp_mesh, stacked):
    """A GPT trained with context_parallel=True follows the same loss curve
    as the gather-based sequence-parallel path (both the per-layer and the
    scan-over-stacked-blocks topologies)."""
    from paddle_tpu import jit, optimizer
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_test_config

    def run(cp):
        paddle.seed(11)
        cfg = gpt_test_config(context_parallel=cp, stacked_blocks=stacked)
        model = parallel.place_model(GPTForCausalLM(cfg))
        crit = GPTPretrainingCriterion(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

        def step(x, y):
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = jit.compile(step, models=[model], optimizers=[opt])
        rng = np.random.RandomState(3)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 32)).astype("int32"))
        lab = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 32)).astype("int32"))
        return [float(compiled(ids, lab)) for _ in range(3)]

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_zigzag_ring_parity_and_grads():
    """layout='zigzag' (balanced causal ring: each device holds
    half-chunks j and 2n-1-j, fully-masked pairs skipped via lax.cond)
    must match the dense causal reference exactly, forward and backward,
    through the permute -> ring -> unpermute path."""
    from paddle_tpu.parallel.ring import ring_attention_arrays
    from paddle_tpu.ops.pallas_ops import mha_reference

    parallel.init_mesh(sp=8)
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 64, 4, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))

    ref = mha_reference(q, k, v, is_causal=True)
    zig = jax.jit(lambda a, b, c: ring_attention_arrays(
        a, b, c, True, None, "sp", layout="zigzag"))(q, k, v)
    np.testing.assert_allclose(np.asarray(zig), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, is_causal=True) ** 2).sum()

    def loss_zig(q, k, v):
        return (ring_attention_arrays(q, k, v, True, None, "sp",
                                      layout="zigzag") ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_zig = jax.jit(jax.grad(loss_zig, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_zig, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)

    # non-causal requests fall back (with a warning) to the contiguous
    # ring; jit the call — partial-manual shard_map is jit-context-only
    import warnings as _w

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        fb = jax.jit(lambda a, b, c: ring_attention_arrays(
            a, b, c, False, None, "sp", layout="zigzag"))(q, k, v)
    assert any("zigzag" in str(x.message) for x in rec)
    ref_nc = mha_reference(q, k, v, is_causal=False)
    np.testing.assert_allclose(np.asarray(fb), np.asarray(ref_nc),
                               rtol=2e-5, atol=2e-5)


def test_gpt_zigzag_layout_training_parity(sp_mesh):
    """cfg.cp_layout='zigzag': the model permutes the token stream once
    (embedding out -> blocks -> unpermute before ln_f) and attention runs
    the balanced zigzag_pre ring — loss trajectory must equal the
    contiguous ring exactly."""
    from paddle_tpu import jit, optimizer
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_test_config)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (4, 64)).astype("int32")
    lab = rng.randint(0, 128, (4, 64)).astype("int32")
    losses = {}
    for layout in ("contiguous", "zigzag"):
        paddle.seed(0)
        cfg = gpt_test_config(num_hidden_layers=2, context_parallel=True,
                              cp_layout=layout, max_position_embeddings=64)
        model = parallel.place_model(GPTForCausalLM(cfg))
        crit = GPTPretrainingCriterion(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def step(x, y):
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = jit.compile(step, models=[model], optimizers=[opt])
        losses[layout] = [
            float(compiled(paddle.to_tensor(ids),
                           paddle.to_tensor(lab)).numpy())
            for _ in range(3)]
    np.testing.assert_allclose(losses["contiguous"], losses["zigzag"],
                               rtol=2e-5)
    assert losses["zigzag"][-1] < losses["zigzag"][0]


def _seg_rows(lengths_per_row, S):
    out = []
    for lens in lengths_per_row:
        ids, pos = [], 0
        for i, ln in enumerate(lens):
            ids += [i] * ln
            pos += ln
        ids += [len(lens)] * (S - pos)
        out.append(ids)
    return jnp.asarray(out, jnp.int32)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_segment_ids_match_dense(sp_mesh, causal):
    """Packed long-context rows keep context parallelism: the k-side ids
    ride the ring with their blocks; parity vs the dense segment-masked
    reference."""
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    segs = _seg_rows([[12, 10, 10], [20, 12]], S)
    ref = mha_reference(q, k, v, None, causal, segment_ids=segs)
    got = jax.jit(lambda q, k, v: ring_attention_arrays(
        q, k, v, causal, segment_ids=segs))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zigzag_ring_segment_ids_match_dense():
    parallel.init_mesh(sp=8)
    try:
        rng = np.random.RandomState(4)
        B, S, H, D = 2, 64, 2, 16
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        segs = _seg_rows([[30, 20, 14], [40, 24]], S)
        ref = mha_reference(q, k, v, None, True, segment_ids=segs)
        got = jax.jit(lambda q, k, v: ring_attention_arrays(
            q, k, v, True, layout="zigzag", segment_ids=segs))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        parallel.init_mesh(dp=1)


@pytest.mark.parametrize("cp_layout", ["contiguous", "zigzag"])
def test_gpt_packed_context_parallel_parity(sp_mesh, cp_layout):
    """Packed segment ids + sp context parallelism end to end: logits on
    the sp=4 mesh match the sp=1 run — both the contiguous ring and the
    model-level zigzag layout (which must permute the segment ids with
    the token stream)."""
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    rng = np.random.RandomState(5)
    ids_np = rng.randint(1, 90, (2, 32)).astype("int32")
    seg_np = np.asarray(_seg_rows([[16, 16], [20, 12]], 32))
    pos_np = np.concatenate([
        np.concatenate([np.arange(16), np.arange(16)])[None],
        np.concatenate([np.arange(20), np.arange(12)])[None]]).astype("int32")

    def run(**mesh):
        paddle.seed(21)
        parallel.init_mesh(**mesh)
        cfg = gpt_test_config(stacked_blocks=True, num_hidden_layers=2,
                              hidden_size=64, intermediate_size=128,
                              num_attention_heads=2,
                              context_parallel=True, cp_layout=cp_layout,
                              max_position_embeddings=32)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m(paddle.to_tensor(ids_np),
                 position_ids=paddle.to_tensor(pos_np),
                 segment_ids=paddle.to_tensor(seg_np)).numpy()

    base = run(dp=1)
    cp = run(dp=2, sp=4)
    np.testing.assert_allclose(cp, base, rtol=2e-4, atol=2e-4)
