"""incubate: ASP 2:4 sparsity + fused transformer stack (reference:
python/paddle/incubate/asp/asp.py, incubate/nn/layer/fused_transformer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate import asp
from paddle_tpu.incubate.nn import FusedMultiTransformer


def test_create_mask_and_check():
    r = np.random.RandomState(0)
    w = r.randn(8, 16).astype("float32")
    mask = asp.create_mask(paddle.to_tensor(w))
    assert mask.shape == w.shape
    # every group of 4 has exactly 2 survivors
    g = mask.reshape(-1, 4)
    np.testing.assert_array_equal(g.sum(1), np.full(len(g), 2.0))
    # the survivors are the 2 largest |w| in each group
    wg = np.abs(w.reshape(-1, 4))
    for i in range(len(g)):
        kept = set(np.nonzero(g[i])[0])
        top2 = set(np.argsort(-wg[i])[:2])
        assert kept == top2
    assert asp.check_sparsity(paddle.to_tensor(w * mask))
    assert not asp.check_sparsity(paddle.to_tensor(w + 1.0))


def test_prune_model_and_decorated_training_keeps_sparsity():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    densities = asp.prune_model(net)
    assert densities  # something was pruned
    for _, p in net.named_parameters():
        if p.ndim >= 2:
            assert abs(asp.calculate_density(p) - 0.5) < 1e-6

    optim = asp.decorate(opt.Adam(1e-2, parameters=net.parameters()))
    r = np.random.RandomState(1)
    x = paddle.to_tensor(r.randn(32, 16).astype("float32"))
    y = paddle.to_tensor(r.randint(0, 4, 32).astype("int64"))
    for _ in range(5):
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
    # sparsity survives optimizer updates
    for _, p in net.named_parameters():
        if p.ndim >= 2:
            assert asp.check_sparsity(p), "mask lost after step"
    asp.reset_excluded_layers()
    asp._masks.clear()


def test_excluded_layers():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 8))
    name = next(n for n, _ in net.named_parameters() if "w" in n or True)
    asp.set_excluded_layers([name])
    pruned = asp.prune_model(net)
    assert name not in pruned
    asp.reset_excluded_layers()
    asp._masks.clear()


def test_fused_multi_transformer_trains():
    paddle.seed(2)
    m = FusedMultiTransformer(32, 4, 64, num_layers=2)
    optim = opt.Adam(1e-3, parameters=m.parameters())
    r = np.random.RandomState(2)
    x = paddle.to_tensor(r.randn(2, 8, 32).astype("float32"))
    target = paddle.to_tensor(r.randn(2, 8, 32).astype("float32"))
    losses = []
    for _ in range(8):
        loss = ((m(x) - target) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]
