"""incubate: ASP 2:4 sparsity + fused transformer stack (reference:
python/paddle/incubate/asp/asp.py, incubate/nn/layer/fused_transformer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate import asp
from paddle_tpu.incubate.nn import FusedMultiTransformer


def test_create_mask_and_check():
    r = np.random.RandomState(0)
    w = r.randn(8, 16).astype("float32")
    mask = asp.create_mask(paddle.to_tensor(w))
    assert mask.shape == w.shape
    # every group of 4 has exactly 2 survivors
    g = mask.reshape(-1, 4)
    np.testing.assert_array_equal(g.sum(1), np.full(len(g), 2.0))
    # the survivors are the 2 largest |w| in each group
    wg = np.abs(w.reshape(-1, 4))
    for i in range(len(g)):
        kept = set(np.nonzero(g[i])[0])
        top2 = set(np.argsort(-wg[i])[:2])
        assert kept == top2
    assert asp.check_sparsity(paddle.to_tensor(w * mask))
    assert not asp.check_sparsity(paddle.to_tensor(w + 1.0))


def test_prune_model_and_decorated_training_keeps_sparsity():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    densities = asp.prune_model(net)
    assert densities  # something was pruned
    for _, p in net.named_parameters():
        if p.ndim >= 2:
            assert abs(asp.calculate_density(p) - 0.5) < 1e-6

    optim = asp.decorate(opt.Adam(1e-2, parameters=net.parameters()))
    r = np.random.RandomState(1)
    x = paddle.to_tensor(r.randn(32, 16).astype("float32"))
    y = paddle.to_tensor(r.randint(0, 4, 32).astype("int64"))
    for _ in range(5):
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
    # sparsity survives optimizer updates
    for _, p in net.named_parameters():
        if p.ndim >= 2:
            assert asp.check_sparsity(p), "mask lost after step"
    asp.reset_excluded_layers()
    asp._masks.clear()


def test_excluded_layers():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 8))
    name = next(n for n, _ in net.named_parameters() if "w" in n or True)
    asp.set_excluded_layers([name])
    pruned = asp.prune_model(net)
    assert name not in pruned
    asp.reset_excluded_layers()
    asp._masks.clear()


@pytest.mark.slow
def test_fused_multi_transformer_trains():
    paddle.seed(2)
    m = FusedMultiTransformer(32, 4, 64, num_layers=2)
    optim = opt.Adam(1e-3, parameters=m.parameters())
    r = np.random.RandomState(2)
    x = paddle.to_tensor(r.randn(2, 8, 32).astype("float32"))
    target = paddle.to_tensor(r.randn(2, 8, 32).astype("float32"))
    losses = []
    for _ in range(8):
        loss = ((m(x) - target) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]


def test_incubate_lazy_jacobian_hessian():
    import numpy as np
    from paddle_tpu.incubate.autograd import Jacobian, Hessian

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))

    def f(a):
        return a * a

    J = Jacobian(f, x)
    assert J.shape == (3, 3)
    np.testing.assert_allclose(J[1, 1].numpy(), 4.0, rtol=1e-6)
    np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0, 6.0]), rtol=1e-6)

    def g(a):
        return (a ** 3).sum()

    H = Hessian(g, x)
    np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0, 18.0]),
                               rtol=1e-5)


def test_incubate_prim_flags_and_modes():
    from paddle_tpu.incubate import autograd as ia

    assert not ia.prim_enabled()
    ia.enable_prim()
    assert ia.prim_enabled()
    ia.disable_prim()
    assert not ia.prim_enabled()

    import numpy as np
    x = paddle.to_tensor(np.array([0.5, 1.5], np.float32))
    t = ia.forward_grad(lambda a: a * a, x)
    r = ia.grad_(lambda a: (a * a).sum(), x)
    np.testing.assert_allclose(np.asarray(t._data), [1.0, 3.0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(r[0]._data if isinstance(r, (list, tuple)) else r._data),
                               [1.0, 3.0], rtol=1e-6)


def test_meta_parallel_wrappers_place_model():
    from paddle_tpu import nn, parallel
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ShardingParallel, TensorParallel)

    parallel.init_mesh(mp=2, sharding=2, dp=2)
    lin = nn.Linear(8, 8)
    tp = TensorParallel(lin)
    assert tp.parameters()
    sp = ShardingParallel(nn.Linear(4, 4))
    out = sp(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert out.shape == (2, 4)


def test_fused_multi_transformer_int8_parity():
    """Int8 (A8W8 dynamic and weight-only) tracks the float layer within
    quantization tolerance (reference test_fused_multi_transformer_int8_op
    parity bound)."""
    from paddle_tpu.incubate.nn import (
        FusedMultiTransformer, FusedMultiTransformerInt8)

    paddle.seed(11)
    fmt = FusedMultiTransformer(32, 4, 64, num_layers=2)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 6, 32)
                         .astype("float32") * 0.5)
    ref = fmt(x).numpy()

    for mode in ("dynamic", "none"):
        q = FusedMultiTransformerInt8.from_float(fmt, act_quant=mode)
        got = q(x).numpy()
        err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.08, (mode, err)
        # int8 weights really are int8; float weight buffers are freed
        wi8, scale = q._qweights[0]["qkv"][:2]
        assert wi8.dtype == np.int8 and scale.dtype == np.float32
        assert q.layers[0]["qkv"].weight._data.ndim == 0
        # state_dict still materializes loadable dequantized weights
        sd = q.state_dict()
        wkey = next(k for k in sd if "qkv" in k and "weight" in k)
        assert sd[wkey].shape == fmt.state_dict()[wkey].shape


@pytest.mark.slow
def test_fused_multi_transformer_int8_cache_decode():
    from paddle_tpu.incubate.nn import (
        FusedMultiTransformer, FusedMultiTransformerInt8)

    paddle.seed(12)
    fmt = FusedMultiTransformer(16, 2, 32, num_layers=1)
    q = FusedMultiTransformerInt8.from_float(fmt)
    x = paddle.to_tensor(np.random.RandomState(1).randn(1, 4, 16)
                         .astype("float32") * 0.5)
    caches = q.gen_cache(1, 8)
    full, _ = q(x, caches=caches, time_step=0)
    # decode one more token against the warm cache
    nxt = paddle.to_tensor(np.random.RandomState(2).randn(1, 1, 16)
                           .astype("float32") * 0.5)
    out, _ = q(nxt, caches=caches, time_step=4)
    assert out.shape == (1, 1, 16)
    assert np.isfinite(out.numpy()).all()


def test_fused_multi_transformer_int8_requires_quantize():
    from paddle_tpu.incubate.nn import FusedMultiTransformerInt8

    q = FusedMultiTransformerInt8(16, 2, 32)
    with pytest.raises(RuntimeError, match="quantize"):
        q(paddle.to_tensor(np.zeros((1, 2, 16), np.float32)))


def test_fused_multi_transformer_int8_propagates_epsilon():
    from paddle_tpu.incubate.nn import (
        FusedMultiTransformer, FusedMultiTransformerInt8)

    fmt = FusedMultiTransformer(16, 2, 32, epsilon=1e-3, dropout_rate=0.2)
    q = FusedMultiTransformerInt8.from_float(fmt)
    assert q.epsilon == 1e-3
    assert q.dropout_rate == 0.2


def test_fused_multi_transformer_int8_cache_len_validated():
    from paddle_tpu.incubate.nn import (
        FusedMultiTransformer, FusedMultiTransformerInt8)

    fmt = FusedMultiTransformer(16, 2, 32, num_layers=2)
    q = FusedMultiTransformerInt8.from_float(fmt)
    x = paddle.to_tensor(np.zeros((1, 2, 16), np.float32))
    with pytest.raises(ValueError, match="caches"):
        q(x, caches=q.gen_cache(1, 8)[:1], time_step=0)


def _np_ec_moe_ref(x, gate, w0, b0, w1, b1):
    """Independent numpy implementation of the reference expert-choice
    algorithm (test_fused_ec_moe_op.py GetBaselineOut)."""
    B, S, D = x.shape
    E = gate.shape[-1]
    cap = max(S // 16, 1)
    e_logits = np.exp(gate - gate.max(-1, keepdims=True))
    probs = e_logits / e_logits.sum(-1, keepdims=True)
    out = np.zeros_like(x)
    for b in range(B):
        for e in range(E):
            top = np.argsort(-gate[b, :, e], kind="stable")[:cap]
            sel = x[b, top]                              # [cap, D]
            h = sel @ w0[e] + b0[e, 0]
            h = 0.5 * h * (1.0 + np.tanh(
                np.sqrt(2.0 / np.pi) * (h + 0.044715 * h ** 3)))
            o = h @ w1[e] + b1[e, 0]
            out[b, top] += o * probs[b, top, e][:, None]
    return x + out


def test_fused_ec_moe_matches_reference_algorithm():
    from paddle_tpu.incubate.nn import fused_ec_moe

    r = np.random.RandomState(3)
    B, S, D, F_, E = 2, 32, 8, 16, 4
    x = r.randn(B, S, D).astype("float32") * 0.5
    gate = r.randn(B, S, E).astype("float32")
    w0 = r.randn(E, D, F_).astype("float32") * 0.1
    b0 = r.randn(E, 1, F_).astype("float32") * 0.1
    w1 = r.randn(E, F_, D).astype("float32") * 0.1
    b1 = r.randn(E, 1, D).astype("float32") * 0.1

    got = fused_ec_moe(paddle.to_tensor(x), paddle.to_tensor(gate),
                       paddle.to_tensor(w0), paddle.to_tensor(b0),
                       paddle.to_tensor(w1), paddle.to_tensor(b1)).numpy()
    want = _np_ec_moe_ref(x, gate, w0, b0, w1, b1)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_fused_ec_moe_layer_trains():
    from paddle_tpu.incubate.nn import FusedEcMoe

    paddle.seed(5)
    moe = FusedEcMoe(8, 16, 4)
    optim = opt.Adam(5e-3, parameters=moe.parameters())
    r = np.random.RandomState(4)
    x = paddle.to_tensor(r.randn(2, 32, 8).astype("float32"))
    gate = paddle.to_tensor(r.randn(2, 32, 4).astype("float32"))
    tgt = paddle.to_tensor(r.randn(2, 32, 8).astype("float32"))
    losses = []
    for _ in range(10):
        loss = ((moe(x, gate) - tgt) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert len(moe.parameters()) == 4


def test_fused_ec_moe_relu_and_bad_act():
    from paddle_tpu.incubate.nn import FusedEcMoe, fused_ec_moe

    with pytest.raises(ValueError, match="act_type"):
        FusedEcMoe(8, 16, 2, act_type="swish")

    # relu branch vs reference algorithm with relu
    r = np.random.RandomState(6)
    B, S, D, F_, E = 1, 32, 4, 8, 2
    x = r.randn(B, S, D).astype("float32") * 0.5
    gate = r.randn(B, S, E).astype("float32")
    w0 = r.randn(E, D, F_).astype("float32") * 0.1
    b0 = r.randn(E, 1, F_).astype("float32") * 0.1
    w1 = r.randn(E, F_, D).astype("float32") * 0.1
    b1 = r.randn(E, 1, D).astype("float32") * 0.1
    got = fused_ec_moe(paddle.to_tensor(x), paddle.to_tensor(gate),
                       paddle.to_tensor(w0), paddle.to_tensor(b0),
                       paddle.to_tensor(w1), paddle.to_tensor(b1),
                       act_type="relu").numpy()
    # reference loop with relu
    cap = max(S // 16, 1)
    e_logits = np.exp(gate - gate.max(-1, keepdims=True))
    probs = e_logits / e_logits.sum(-1, keepdims=True)
    want = x.copy()
    for b in range(B):
        for e in range(E):
            top = np.argsort(-gate[b, :, e], kind="stable")[:cap]
            h = np.maximum(x[b, top] @ w0[e] + b0[e, 0], 0.0)
            o = h @ w1[e] + b1[e, 0]
            want[b, top] += o * probs[b, top, e][:, None]
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_lbfgs_quadratic_exact():
    """L-BFGS on a quadratic reaches the exact minimum in a few steps."""
    from paddle_tpu.incubate.optimizer import LBFGS

    A = np.array([[3.0, 0.5], [0.5, 1.0]], np.float32)
    b = np.array([1.0, -2.0], np.float32)
    x = paddle.to_tensor(np.zeros(2, np.float32), stop_gradient=False)
    optim = LBFGS(learning_rate=1.0, max_iter=30, parameters=[x],
                  line_search_fn="strong_wolfe")

    def closure():
        optim.clear_grad()
        loss = 0.5 * (x.reshape([1, 2]) @ paddle.to_tensor(A)
                      @ x.reshape([2, 1])).sum() - (
            x * paddle.to_tensor(b)).sum()
        loss.backward()
        return loss

    optim.step(closure)
    want = np.linalg.solve(A, b)
    np.testing.assert_allclose(x.numpy(), want, rtol=1e-4, atol=1e-4)


def test_lbfgs_rosenbrock_beats_sgd():
    from paddle_tpu.incubate.optimizer import LBFGS

    def make():
        return paddle.to_tensor(np.array([-1.2, 1.0], np.float32),
                                stop_gradient=False)

    def rosen(t):
        a, b_ = t[0], t[1]
        return (1 - a) ** 2 + 100 * (b_ - a * a) ** 2

    xl = make()
    lb = LBFGS(learning_rate=1.0, max_iter=40, parameters=[xl],
               line_search_fn="strong_wolfe")

    def closure():
        lb.clear_grad()
        loss = rosen(xl)
        loss.backward()
        return loss

    for _ in range(5):
        lb.step(closure)
    final = float(rosen(xl))
    assert final < 1e-3, final
    np.testing.assert_allclose(xl.numpy(), [1.0, 1.0], atol=0.05)


def test_lbfgs_validates():
    from paddle_tpu.incubate.optimizer import LBFGS

    with pytest.raises(ValueError):
        LBFGS(parameters=None)
    with pytest.raises(ValueError):
        LBFGS(parameters=[paddle.to_tensor([1.0])], line_search_fn="armijo")


def test_lbfgs_weight_decay_and_clip_applied():
    from paddle_tpu.incubate.optimizer import LBFGS
    from paddle_tpu.optimizer.clip import ClipGradByValue

    x = paddle.to_tensor(np.array([10.0], np.float32), stop_gradient=False)
    # pure weight decay: loss 0, grad = wd * x, one unit step moves x down
    optim = LBFGS(learning_rate=0.1, max_iter=1, parameters=[x],
                  weight_decay=0.5)

    def closure():
        optim.clear_grad()
        loss = (x * 0.0).sum()
        loss.backward()
        return loss

    before = float(x.numpy()[0])
    optim.step(closure)
    assert float(x.numpy()[0]) < before  # decay pulled it toward 0

    y = paddle.to_tensor(np.array([0.0], np.float32), stop_gradient=False)
    clip = ClipGradByValue(0.1)
    opt2 = LBFGS(learning_rate=1.0, max_iter=1, parameters=[y],
                 grad_clip=clip)

    def closure2():
        opt2.clear_grad()
        loss = (y * 1000.0).sum()
        loss.backward()
        return loss

    opt2.step(closure2)
    # raw grad 1000 would move y by ~ -1000 * |scaled d|; the clip caps
    # the flat grad magnitude to 0.1 so the first (scaled) step is tiny
    assert abs(float(y.numpy()[0])) < 1.0


def test_lbfgs_respects_eval_budget():
    from paddle_tpu.incubate.optimizer import LBFGS

    calls = {"n": 0}
    x = paddle.to_tensor(np.array([-1.2, 1.0], np.float32),
                         stop_gradient=False)
    optim = LBFGS(learning_rate=1.0, max_iter=50, max_eval=8,
                  parameters=[x], line_search_fn="strong_wolfe")

    def closure():
        calls["n"] += 1
        optim.clear_grad()
        a, b_ = x[0], x[1]
        loss = (1 - a) ** 2 + 100 * (b_ - a * a) ** 2
        loss.backward()
        return loss

    optim.step(closure)
    # bracketing may overshoot by at most one probe per phase
    assert calls["n"] <= 8 + 3, calls["n"]


def test_incubate_nn_functional_surface():
    from paddle_tpu.incubate.nn import functional as IF

    r = np.random.RandomState(7)
    x = paddle.to_tensor(r.randn(2, 4, 8).astype("float32"))
    w = paddle.to_tensor(r.randn(8, 8).astype("float32"))
    b = paddle.to_tensor(r.randn(8).astype("float32"))

    out = IF.fused_linear(x, w, b)
    np.testing.assert_allclose(out.numpy(), x.numpy() @ w.numpy() + b.numpy(),
                               rtol=1e-5, atol=1e-5)
    out_t = IF.fused_matmul_bias(x, w, b, transpose_y=True)
    np.testing.assert_allclose(out_t.numpy(),
                               x.numpy() @ w.numpy().T + b.numpy(),
                               rtol=1e-5, atol=1e-5)

    res = paddle.to_tensor(r.randn(2, 4, 8).astype("float32"))
    ln = IF.fused_bias_dropout_residual_layer_norm(
        x, res, dropout_rate=0.0, training=False)
    # matches manual compose
    want = nn.functional.layer_norm(x + res, normalized_shape=[8])
    np.testing.assert_allclose(ln.numpy(), want.numpy(), rtol=1e-4, atol=1e-4)

    w1 = paddle.to_tensor(r.randn(8, 16).astype("float32"))
    w2 = paddle.to_tensor(r.randn(16, 8).astype("float32"))
    ff = IF.fused_feedforward(x, w1, w2, dropout1_rate=0.0, dropout2_rate=0.0,
                              training=False)
    assert ff.shape == (2, 4, 8)

    qkv_w = paddle.to_tensor(r.randn(8, 24).astype("float32"))
    lin_w = paddle.to_tensor(r.randn(8, 8).astype("float32"))
    at = IF.fused_multi_head_attention(
        x, qkv_w, lin_w, num_heads=2, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    assert at.shape == (2, 4, 8)
    assert np.isfinite(at.numpy()).all()
