"""Serialization round-trip fuzzing: random layer stacks through
jit.save -> jit.load (TranslatedLayer analog) and
save_inference_model -> Predictor (AnalysisPredictor analog), asserting
output parity with the live model — the composition coverage the
targeted save/load tests don't reach (conv/BN/pool/activation mixes,
multiple dtypes of input, eval-mode buffers).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn

pytestmark = pytest.mark.slow


def _random_stack(rng):
    """A random eval-mode model: conv trunk then MLP head."""
    layers = []
    c = 3
    for _ in range(rng.randint(1, 3)):
        c_out = int(rng.choice([4, 8]))
        layers.append(nn.Conv2D(c, c_out, 3, padding=1))
        if rng.rand() < 0.5:
            layers.append(nn.BatchNorm2D(c_out))
        layers.append([nn.ReLU(), nn.GELU(), nn.Sigmoid()][rng.randint(3)])
        if rng.rand() < 0.5:
            layers.append(nn.MaxPool2D(2, 2))
        c = c_out
    layers.append(nn.AdaptiveAvgPool2D(1))
    layers.append(nn.Flatten())
    layers.append(nn.Linear(c, int(rng.choice([2, 5]))))
    return nn.Sequential(*layers)


@pytest.mark.parametrize("seed", range(4))
def test_jit_save_load_roundtrip_fuzz(seed, tmp_path):
    rng = np.random.RandomState(seed)
    paddle.seed(seed)
    model = _random_stack(rng)
    model.eval()
    x = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype("float32"))
    ref = model(x).numpy()

    path = str(tmp_path / f"m{seed}")
    jit.save(model, path, input_spec=[x])
    loaded = jit.load(path)
    out = loaded(x)
    out = out[0] if isinstance(out, (list, tuple)) else out
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("seed", range(3))
def test_inference_predictor_roundtrip_fuzz(seed, tmp_path):
    from paddle_tpu import inference

    rng = np.random.RandomState(10 + seed)
    paddle.seed(seed)
    model = _random_stack(rng)
    model.eval()
    x = rng.randn(2, 3, 16, 16).astype("float32")
    ref = model(paddle.to_tensor(x)).numpy()

    path = str(tmp_path / f"p{seed}")
    inference.save_inference_model(path, model,
                                   example_inputs=[paddle.to_tensor(x)])
    cfg = inference.Config(prog_file=path)
    pred = inference.create_predictor(cfg)
    in_names = pred.get_input_names()
    h = pred.get_input_handle(in_names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-6)
