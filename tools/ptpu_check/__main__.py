"""CLI: ``python -m tools.ptpu_check [--json] [paths...]``.

Exit codes: 0 = clean, 1 = unsuppressed findings (or marker/syntax
errors), 2 = usage error.  ``--json`` prints the machine report to
stdout; ``--json-out FILE`` writes it AND keeps the human report on
stdout (the CI artifact path).
"""
from __future__ import annotations

import argparse
import json
import re
import sys

from . import __version__
from .api import (DEFAULT_BASELINE, DEFAULT_PATHS, run_check,
                  write_baseline)
from .core import collect_files
from .rules import ALL_RULES

def migrate_legacy(paths, repo_root):
    """Mechanically rewrite the legacy ``justified:`` / ``metric-ok:``
    comment tags to the unified ``ptpu-check[<rule>]:`` scheme,
    preserving every word of justification text.  Real COMMENT tokens
    only (via ``tokenize``) — a ``'# justified: ...'`` inside a string
    literal (test fixtures, docs) is data, not a marker, and survives
    untouched.  Tags mid-comment (after a trailing ``pass``) rewrite the
    same way.  Idempotent: comments already carrying ``ptpu-check[`` are
    skipped."""
    import io
    import tokenize as tok

    just = re.compile(r"justified:\s?")
    mok = re.compile(r"metric-ok:\s?")
    changed = []
    for fp, rel in collect_files(paths, repo_root):
        with open(fp, encoding="utf-8") as f:
            src = f.read()
        try:
            tokens = list(tok.generate_tokens(io.StringIO(src).readline))
        except (tok.TokenError, IndentationError, SyntaxError):
            continue   # un-tokenizable file: leave it alone
        lines = src.splitlines(keepends=True)
        touched = False
        for t in tokens:
            if t.type != tok.COMMENT or "ptpu-check[" in t.string:
                continue
            new = t.string
            if "justified:" in new:
                new = just.sub("ptpu-check[silent-except]: ", new, count=1)
            if "metric-ok:" in new:
                new = mok.sub("ptpu-check[metric-hygiene]: ", new, count=1)
            if new != t.string:
                row, col = t.start
                ln = lines[row - 1]
                lines[row - 1] = ln[:col] + new + ln[col + len(t.string):]
                touched = True
        if touched:
            with open(fp, "w", encoding="utf-8") as f:
                f.writelines(lines)
            changed.append(rel)
    return changed


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.ptpu_check",
        description="paddle_tpu unified static analyzer (see README "
                    "'Static analysis' for the rules and their history)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to analyze (default: "
                         f"{' '.join(DEFAULT_PATHS)} under the repo root)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report to stdout")
    ap.add_argument("--json-out", metavar="FILE",
                    help="also write the JSON report to FILE (CI "
                         "artifact)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE, metavar="FILE",
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as live")
    ap.add_argument("--write-baseline", action="store_true",
                    help="absorb ALL current findings into the baseline "
                         "(the audit workflow) and exit 0")
    ap.add_argument("--changed", metavar="GIT_REF",
                    help="incremental mode: run rules only on files "
                         "changed vs GIT_REF (worktree diff + "
                         "untracked) plus their call-graph closure; "
                         "the whole tree is still parsed for "
                         "reachability")
    ap.add_argument("--rules", metavar="ID[,ID...]",
                    help="run only these rules")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--migrate-legacy", action="store_true",
                    help="rewrite the legacy justified:/metric-ok: "
                         "comment tags to ptpu-check[<rule>]: in place")
    ap.add_argument("--version", action="version", version=__version__)
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:18s} {r.doc}")
            print(f"{'':18s}   descends from: {r.descends_from}")
        return 0

    from .api import REPO_ROOT
    paths = args.paths or None

    if args.migrate_legacy:
        target = paths or [p for p in DEFAULT_PATHS]
        import os
        target = [p if os.path.isabs(p) else os.path.join(REPO_ROOT, p)
                  for p in target]
        target = [p for p in target if os.path.exists(p)]
        changed = migrate_legacy(target, REPO_ROOT)
        for rel in changed:
            print(f"migrated: {rel}")
        print(f"ptpu_check: migrated {len(changed)} file(s)")
        return 0

    if args.write_baseline and args.changed:
        # the baseline is regenerated from the CURRENT findings — under
        # --changed that is only the incremental closure's findings, and
        # writing it would silently wipe every audited entry for files
        # outside the closure
        print("ptpu_check: --write-baseline requires a whole-tree run; "
              "drop --changed (the baseline must absorb ALL current "
              "findings, not the incremental closure's)",
              file=sys.stderr)
        return 2

    rule_ids = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    try:
        report, project = run_check(
            paths=paths, rule_ids=rule_ids, baseline_path=args.baseline,
            use_baseline=not args.no_baseline, changed_ref=args.changed)
    except ValueError as e:
        print(f"ptpu_check: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        bl = write_baseline(report, project, args.baseline)
        n = sum(bl.entries.values())
        print(f"ptpu_check: baseline written with {n} audited "
              f"finding(s) -> {args.baseline}")
        return 0

    doc = report.as_json()
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=False)
            f.write("\n")
    if args.json:
        json.dump(doc, sys.stdout, indent=1, sort_keys=False)
        print()
    else:
        for f in report.errors + report.new:
            print(f.render())
        n, b = len(report.new), len(report.baselined)
        status = "clean" if report.clean else \
            f"{n + len(report.errors)} violation(s)"
        extra = f", {b} baselined" if b else ""
        if report.incremental is not None:
            inc = report.incremental
            extra += (f"; --changed {inc['ref']}: "
                      f"{len(inc['changed'])} changed -> "
                      f"{len(inc['analyzed'])} analyzed")
        print(f"ptpu_check: {status} ({len(project.contexts)} files, "
              f"{report.elapsed_s:.1f}s{extra})")
    return 0 if report.clean else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # `... | head` closed the pipe: not an error
        sys.exit(0)
