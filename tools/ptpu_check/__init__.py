"""ptpu-check — the repo's unified whole-program static analyzer.

Every rule in here mechanizes a bug class a review round actually fixed
by hand (see CHANGES.md / README "Static analysis").  One shared
``ast.parse`` per file, a cross-file call graph for reachability-based
rules, per-rule inline suppressions, and a checked-in baseline for
audited pre-existing sites.

CLI::

    python -m tools.ptpu_check [--json] [--json-out FILE] [paths...]

Library::

    from tools.ptpu_check.api import run_check
"""
from __future__ import annotations

__version__ = "1.0"
