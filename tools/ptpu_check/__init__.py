"""ptpu-check — the repo's unified whole-program static analyzer.

Every rule in here mechanizes a bug class a review round actually fixed
by hand (see CHANGES.md / README "Static analysis").  One shared
``ast.parse`` per file, a cross-file call graph for reachability-based
rules, per-rule inline suppressions, and a checked-in baseline for
audited pre-existing sites.

v2 (ISSUE 14) extends the core interprocedurally for the multi-process
era: acquire/release escape analysis (resource-leak), handler-context
reachability (blocking-in-handler), the static twin of the runtime
recompile explainer (recompile-hazard), a declared wire registry
(wire-compat), README env-flag cross-checking (env-flag-drift), plus
``--changed <git-ref>`` incremental mode (rules only on the changed
files' call-graph closure) and the call-graph alias/self-attr fixes.

CLI::

    python -m tools.ptpu_check [--json] [--json-out FILE]
                               [--changed GIT_REF] [paths...]

Library::

    from tools.ptpu_check.api import run_check
"""
from __future__ import annotations

__version__ = "2.0"
