"""Library entry point: run the analyzer over paths, partition against
the baseline, and report — the CLI and the test suite both drive this.

Incremental mode (``--changed <git-ref>``): every file is still PARSED
(the call graph needs the whole tree — reachability is global), but the
RULES — the expensive 80% — run only on files changed vs the ref plus
their call-graph closure (callers of changed functions, whose findings
can appear/vanish when a callee changes, AND callees reached by changed
functions, where a changed caller can put a new jit entry / handler
context above unchanged code).  The fast CI lane pays ~2 s of
parse+graph instead of the whole-tree rule wall.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

from . import rules as rules_pkg
from .core import Baseline, Finding, Project, collect_files, load_context

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
DEFAULT_PATHS = ("paddle_tpu", "tools", "scripts")
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


class Report:
    def __init__(self, new, baselined, errors, rules, paths, elapsed_s,
                 incremental=None):
        self.new = new                 # unsuppressed, non-baselined
        self.baselined = baselined
        self.errors = errors           # syntax errors etc.
        self.rules = rules
        self.paths = paths
        self.elapsed_s = elapsed_s
        self.incremental = incremental  # {ref, changed, analyzed} or None

    @property
    def clean(self):
        return not self.new and not self.errors

    def as_json(self) -> dict:
        # schema v2 (ISSUE 14): adds the `incremental` block (null on
        # whole-tree runs).  v1 keys are byte-identical otherwise —
        # consumers keying on `counts`/`findings` are unaffected.
        return {
            "version": 2,
            "tool": "ptpu_check",
            "rules": [r.id for r in self.rules],
            "paths": list(self.paths),
            "incremental": self.incremental,
            "counts": {"findings": len(self.new),
                       "baselined": len(self.baselined),
                       "errors": len(self.errors)},
            "findings": [f.as_json() for f in self.new],
            "baselined": [f.as_json() for f in self.baselined],
            "errors": [f.as_json() for f in self.errors],
        }


def _git_changed(repo_root, ref):
    """Repo-relative .py files changed vs `ref` (worktree diff +
    untracked).  Raises RuntimeError when git cannot answer."""
    def lines(args):
        p = subprocess.run(["git", *args], cwd=repo_root,
                           capture_output=True, text=True, timeout=30)
        if p.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: {p.stderr.strip()}")
        return [ln for ln in p.stdout.splitlines() if ln.endswith(".py")]

    changed = set(lines(["diff", "--name-only", ref, "--", "*.py"]))
    changed.update(lines(["ls-files", "--others", "--exclude-standard",
                          "--", "*.py"]))
    return changed


def run_check(paths=None, repo_root=None, rule_ids=None,
              baseline_path=DEFAULT_BASELINE, use_baseline=True,
              changed_ref=None):
    """Analyze `paths` (default: paddle_tpu/ tools/ scripts/) and return
    a Report.  One parse per file; rules share the parse and the lazily
    built call graph.  `changed_ref` switches to incremental mode:
    rules run only on files changed vs that git ref plus their
    call-graph closure (the whole tree is still parsed for
    reachability).  A git failure falls back to the full analysis with
    a warning — incremental mode must never hide findings because the
    ref was bad."""
    t0 = time.perf_counter()
    repo_root = os.path.abspath(repo_root or REPO_ROOT)
    if not paths:
        paths = [os.path.join(repo_root, p) for p in DEFAULT_PATHS
                 if os.path.isdir(os.path.join(repo_root, p))]
    rule_classes = rules_pkg.ALL_RULES
    if rule_ids:
        unknown = set(rule_ids) - set(rules_pkg.RULES_BY_ID)
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {sorted(unknown)}; known: "
                f"{sorted(rules_pkg.RULES_BY_ID)}")
        rule_classes = [rules_pkg.RULES_BY_ID[r] for r in rule_ids]

    contexts, errors = [], []
    for fp, rel in collect_files(paths, repo_root):
        ctx = load_context(fp, rel)
        contexts.append(ctx)
        if ctx.syntax_error is not None:
            e = ctx.syntax_error
            errors.append(Finding("syntax-error", ctx.rel, e.lineno or 0,
                                  0, f"syntax error: {e.msg}"))
    project = Project(contexts, repo_root=repo_root)

    incremental = None
    target_rels = None
    if changed_ref:
        try:
            changed = _git_changed(repo_root, changed_ref)
        except (RuntimeError, OSError) as e:
            print(f"ptpu_check: --changed fell back to full analysis "
                  f"({e})", file=sys.stderr)
            changed = None
        if changed is not None:
            in_scope = sorted(changed & set(project.by_rel))
            target_rels = project.callgraph.file_closure(in_scope)
            incremental = {"ref": changed_ref, "changed": in_scope,
                           "analyzed": sorted(target_rels)}

    findings = []
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        if target_rels is not None and ctx.rel not in target_rels:
            continue
        for line in ctx.bare_markers():
            errors.append(Finding(
                "marker-hygiene", ctx.rel, line, 0,
                "`# ptpu-check[...]` marker without a justification — "
                "every suppression documents WHY"))
        known = set(rules_pkg.RULES_BY_ID)
        for line, ids in sorted(ctx.markers.items()):
            bad = ids - known
            if bad:
                errors.append(Finding(
                    "marker-hygiene", ctx.rel, line, 0,
                    f"marker names unknown rule(s) {sorted(bad)}; known: "
                    f"{sorted(known)}"))
        for rule_cls in rule_classes:
            findings.extend(rule_cls().check(ctx, project))

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    errors.sort(key=lambda f: (f.path, f.line, f.rule))
    if use_baseline:
        baseline = Baseline.load(baseline_path)
        new, old = baseline.partition(findings, project.by_rel)
    else:
        new, old = findings, []
    return Report(new, old, errors, rule_classes, paths,
                  time.perf_counter() - t0,
                  incremental=incremental), project


def write_baseline(report, project, baseline_path=DEFAULT_BASELINE):
    """Absorb every CURRENT finding (new + already-baselined) into the
    baseline file — the audit workflow after reviewing them."""
    bl = Baseline.from_findings(report.new + report.baselined,
                                project.by_rel)
    bl.save(baseline_path)
    return bl
