"""Library entry point: run the analyzer over paths, partition against
the baseline, and report — the CLI and the test suite both drive this.
"""
from __future__ import annotations

import os
import time

from . import rules as rules_pkg
from .core import Baseline, Finding, Project, collect_files, load_context

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
DEFAULT_PATHS = ("paddle_tpu", "tools", "scripts")
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


class Report:
    def __init__(self, new, baselined, errors, rules, paths, elapsed_s):
        self.new = new                 # unsuppressed, non-baselined
        self.baselined = baselined
        self.errors = errors           # syntax errors etc.
        self.rules = rules
        self.paths = paths
        self.elapsed_s = elapsed_s

    @property
    def clean(self):
        return not self.new and not self.errors

    def as_json(self) -> dict:
        return {
            "version": 1,
            "tool": "ptpu_check",
            "rules": [r.id for r in self.rules],
            "paths": list(self.paths),
            "counts": {"findings": len(self.new),
                       "baselined": len(self.baselined),
                       "errors": len(self.errors)},
            "findings": [f.as_json() for f in self.new],
            "baselined": [f.as_json() for f in self.baselined],
            "errors": [f.as_json() for f in self.errors],
        }


def run_check(paths=None, repo_root=None, rule_ids=None,
              baseline_path=DEFAULT_BASELINE, use_baseline=True):
    """Analyze `paths` (default: paddle_tpu/ tools/ scripts/) and return
    a Report.  One parse per file; rules share the parse and the lazily
    built call graph."""
    t0 = time.perf_counter()
    repo_root = os.path.abspath(repo_root or REPO_ROOT)
    if not paths:
        paths = [os.path.join(repo_root, p) for p in DEFAULT_PATHS
                 if os.path.isdir(os.path.join(repo_root, p))]
    rule_classes = rules_pkg.ALL_RULES
    if rule_ids:
        unknown = set(rule_ids) - set(rules_pkg.RULES_BY_ID)
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {sorted(unknown)}; known: "
                f"{sorted(rules_pkg.RULES_BY_ID)}")
        rule_classes = [rules_pkg.RULES_BY_ID[r] for r in rule_ids]

    contexts, errors = [], []
    for fp, rel in collect_files(paths, repo_root):
        ctx = load_context(fp, rel)
        contexts.append(ctx)
        if ctx.syntax_error is not None:
            e = ctx.syntax_error
            errors.append(Finding("syntax-error", ctx.rel, e.lineno or 0,
                                  0, f"syntax error: {e.msg}"))
    project = Project(contexts)

    findings = []
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        for line in ctx.bare_markers():
            errors.append(Finding(
                "marker-hygiene", ctx.rel, line, 0,
                "`# ptpu-check[...]` marker without a justification — "
                "every suppression documents WHY"))
        known = set(rules_pkg.RULES_BY_ID)
        for line, ids in sorted(ctx.markers.items()):
            bad = ids - known
            if bad:
                errors.append(Finding(
                    "marker-hygiene", ctx.rel, line, 0,
                    f"marker names unknown rule(s) {sorted(bad)}; known: "
                    f"{sorted(known)}"))
        for rule_cls in rule_classes:
            findings.extend(rule_cls().check(ctx, project))

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    errors.sort(key=lambda f: (f.path, f.line, f.rule))
    if use_baseline:
        baseline = Baseline.load(baseline_path)
        new, old = baseline.partition(findings, project.by_rel)
    else:
        new, old = findings, []
    return Report(new, old, errors, rule_classes, paths,
                  time.perf_counter() - t0), project


def write_baseline(report, project, baseline_path=DEFAULT_BASELINE):
    """Absorb every CURRENT finding (new + already-baselined) into the
    baseline file — the audit workflow after reviewing them."""
    bl = Baseline.from_findings(report.new + report.baselined,
                                project.by_rel)
    bl.save(baseline_path)
    return bl
