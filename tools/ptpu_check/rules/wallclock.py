"""wall-clock — ``time.time()`` arithmetic used for elapsed/deadline
math.

The bug class: wall clock steps under NTP slew, VM migration and
suspend/resume — a duration computed as ``time.time() - t0`` can be
negative or hours long, which turns watchdog/deadline/heartbeat logic
into a false-trigger machine.  Durations and deadlines belong on
``time.monotonic()`` / ``time.perf_counter()``; ``time.time()`` is
ONLY for timestamps that get exported (logs, dump files, cross-process
heartbeat values).

Flagged: any ``+``/``-`` arithmetic where an operand is a direct
``time.time()`` call, a local name bound to one, or a ``self.X``
attribute bound to one anywhere in the same class.  Plain
``{"ts": time.time()}`` exports are not flagged.

Suppress with ``# ptpu-check[wall-clock]: why`` — the legitimate case
is CROSS-PROCESS timestamp comparison (one process wrote the wall-clock
value, another subtracts it; monotonic clocks don't travel between
hosts).
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name
from ..core import Rule


def _is_walltime_call(node, time_aliases) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    if dn is None:
        return False
    parts = dn.split(".")
    return len(parts) == 2 and parts[0] in time_aliases \
        and parts[1] == "time"


class WallClockRule(Rule):
    id = "wall-clock"
    doc = ("elapsed/deadline math uses monotonic()/perf_counter(), "
           "never time.time() subtraction")
    descends_from = ("9+ modules measured durations off the wall clock "
                     "(store deadlines, elastic grace windows, hapi "
                     "step timing); an NTP step would fire every one "
                     "of them at once")

    def check(self, ctx, project):
        idx = project.callgraph.index_of(ctx.rel)
        time_aliases = {"time"}
        if idx is not None:
            time_aliases = {n for n, mod in idx.mod_alias.items()
                            if mod == "time"} or {"time"}

        # class-level: self.X = time.time() anywhere in the class
        class_attrs = {}   # ClassDef -> {attr names}
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs = set()
            for n in ast.walk(cls):
                if isinstance(n, ast.Assign) and \
                        _is_walltime_call(n.value, time_aliases):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            attrs.add(t.attr)
            if attrs:
                class_attrs[cls] = attrs

        def scan_scope(body, names, self_attrs):
            for stmt in body:
                yield from visit(stmt, names, self_attrs)

        def visit(node, names, self_attrs):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from scan_scope(node.body, set(), self_attrs)
                return
            if isinstance(node, ast.ClassDef):
                yield from scan_scope(node.body, set(),
                                      class_attrs.get(node, set()))
                return
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                if _is_walltime_call(node.value, time_aliases):
                    names.add(node.targets[0].id)
                else:
                    names.discard(node.targets[0].id)
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, (ast.Add, ast.Sub)):
                for side in (node.left, node.right):
                    if self._is_wall(side, names, self_attrs,
                                     time_aliases):
                        if not ctx.suppressed(self.id, node.lineno):
                            yield self.finding(
                                ctx, node,
                                "elapsed/deadline arithmetic on "
                                "time.time() — the wall clock steps "
                                "(NTP/suspend); use time.monotonic() or "
                                "time.perf_counter(), keep time.time() "
                                "only for exported timestamps")
                        break
            for child in ast.iter_child_nodes(node):
                yield from visit(child, names, self_attrs)

        yield from scan_scope(ctx.tree.body, set(), set())

    @staticmethod
    def _is_wall(node, names, self_attrs, time_aliases):
        if _is_walltime_call(node, time_aliases):
            return True
        if isinstance(node, ast.Name) and node.id in names:
            return True
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in self_attrs:
            return True
        return False
