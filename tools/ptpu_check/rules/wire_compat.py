"""wire-compat — cross-process protocol surfaces checked against the ONE
declared registry (``paddle_tpu/monitor/wire.py``).

The bug class: the rpc frame, the ``/healthz`` ``schema_version`` and
the fleet router-feed keys are spoken by version-skewed processes — an
old aggregator scraping a new replica, a mid-deploy rpc client dialing
an un-upgraded server.  PRs 9–11 each adjusted one of these surfaces by
hand and leaned on review to keep the sides consistent; this rule makes
the registry the single source of truth and flags drift statically.

The registry is discovered INSIDE the analyzed file set: the module
that declares at least two of ``RPC_FRAME_MIN``/``RPC_FRAME_MAX``/
``HEALTHZ_SCHEMA_VERSION``/``FLEET_HEALTHZ_SCHEMA_VERSION``/
``ROUTER_FEED_KEYS`` as module-level literals.  No registry in scope →
the rule is silent (partial-path runs stay usable); TWO registries is
itself a finding.

Checks:

- ``"schema_version": <int>`` dict keys: the literal must equal one of
  the registry's declared ``*_SCHEMA_VERSION`` values (a Name/Attribute
  reference to a ``*SCHEMA_VERSION`` constant is always fine — that IS
  the registry);
- ``# ptpu-wire: router-feed`` / ``# ptpu-wire: reqlog-event``-anchored
  dict literals: their string keys must equal ``ROUTER_FEED_KEYS`` /
  ``REQLOG_EVENT_KEYS`` exactly, both directions — a key added to the
  surface but not the registry breaks the accrete-only contract
  silently, a registry key missing from the surface is a phantom its
  consumers will read as absent forever;
- rpc frame shapes in modules that speak the frame (reference
  ``_send_frame``/``_recv_frame``): tuple literals whose first elements
  are ``(fn, args, ...)`` must have arity within
  ``[RPC_FRAME_MIN, RPC_FRAME_MAX]``; mandatory-field slices
  ``msg[:k]`` must cut exactly ``RPC_FRAME_MIN``; optional-field probes
  ``len(msg) > k`` must probe within the declared range.

Suppress with ``# ptpu-check[wire-compat]: why`` (e.g. a fixture that
deliberately speaks an old frame).
"""
from __future__ import annotations

import ast

from ..core import Rule

REGISTRY_NAMES = {"RPC_FRAME_MIN", "RPC_FRAME_MAX",
                  "HEALTHZ_SCHEMA_VERSION",
                  "FLEET_HEALTHZ_SCHEMA_VERSION", "ROUTER_FEED_KEYS",
                  "REQLOG_SCHEMA_VERSION", "REQLOG_EVENT_KEYS",
                  "ROUTER_SCHEMA_VERSION", "ROUTER_SUBMIT_KEYS",
                  "ROUTER_RESULT_KEYS", "ROUTER_HANDOFF_KEYS",
                  "ROUTER_POLL_KEYS", "ROUTER_METRIC_NAMES",
                  "API_ERROR_KEYS"}
# anchored dict literals: each anchor comment pins the dict's string
# keys to one declared key tuple (ISSUE 16 added the reqlog event to
# the router feed's original contract; ISSUE 17 the router↔replica
# frames and the router metric-name set; ISSUE 19 the HTTP API error
# body)
ANCHORED_KEYS = {"ptpu-wire: router-feed": "ROUTER_FEED_KEYS",
                 "ptpu-wire: reqlog-event": "REQLOG_EVENT_KEYS",
                 "ptpu-wire: router-submit": "ROUTER_SUBMIT_KEYS",
                 "ptpu-wire: router-result": "ROUTER_RESULT_KEYS",
                 "ptpu-wire: router-handoff": "ROUTER_HANDOFF_KEYS",
                 "ptpu-wire: router-poll": "ROUTER_POLL_KEYS",
                 "ptpu-wire: router-metrics": "ROUTER_METRIC_NAMES",
                 "ptpu-wire: api-error": "API_ERROR_KEYS"}


def _module_literals(ctx):
    """{NAME: python value} for module-level constant assignments."""
    out = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name not in REGISTRY_NAMES:
                continue
            try:
                out[name] = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                pass
    return out


def _find_registry(project):
    """(rel, constants dict) for the one wire registry in scope, plus
    every extra registry rel (a finding each)."""
    if getattr(project, "_wire_registry", None) is not None:
        return project._wire_registry
    hits = []
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        consts = _module_literals(ctx)
        if len(consts) >= 2:
            hits.append((ctx.rel, consts))
    primary = hits[0] if hits else (None, {})
    project._wire_registry = (primary[0], primary[1],
                              [rel for rel, _ in hits[1:]])
    return project._wire_registry


def _is_schema_name(expr) -> bool:
    """Name/Attribute whose terminal segment is a *SCHEMA_VERSION
    constant — a reference INTO the registry, fine by construction."""
    if isinstance(expr, ast.Attribute):
        return expr.attr.endswith("SCHEMA_VERSION")
    if isinstance(expr, ast.Name):
        return expr.id.endswith("SCHEMA_VERSION")
    return False


class WireCompatRule(Rule):
    id = "wire-compat"
    doc = ("rpc frame arity, /healthz schema_version, and router-feed "
           "keys must match the declared wire registry (monitor/wire.py)")
    descends_from = ("PR-9: the rpc 4-tuple frame vs legacy 3-tuple "
                     "servers, /healthz schema bumps, and the accrete-"
                     "only router feed were each kept consistent by hand "
                     "across version-skewed fleets")

    def check(self, ctx, project):
        reg_rel, consts, extras = _find_registry(project)
        if reg_rel is None:
            return
        if ctx.rel in extras:
            node = ctx.tree.body[0] if ctx.tree.body else ctx.tree
            yield self.finding(
                ctx, node,
                f"second wire registry (the one source of truth is "
                f"{reg_rel}) — merge the declarations")
        schema_versions = {v for k, v in consts.items()
                           if k.endswith("SCHEMA_VERSION")
                           and isinstance(v, int)}
        frame_min = consts.get("RPC_FRAME_MIN")
        frame_max = consts.get("RPC_FRAME_MAX")
        if ctx.rel == reg_rel:
            return   # the registry itself is the truth, not a speaker

        # {keys-const-name: [anchor line numbers]} for every anchored
        # surface this file speaks
        anchors: dict = {}
        for i, ln in enumerate(ctx.lines, start=1):
            h = ln.find("#")
            if h < 0:
                continue   # anchors are COMMENTS: a string literal
            #              # mentioning one (this table!) is not a pin
            for text, const in ANCHORED_KEYS.items():
                if text in ln[h:]:
                    anchors.setdefault(const, []).append(i)
        speaks_rpc = ("_send_frame" in ctx.src or "_recv_frame" in ctx.src)

        for node in ast.walk(ctx.tree):
            # -- /healthz schema_version ------------------------------
            if isinstance(node, ast.Dict) and schema_versions:
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) \
                            and k.value == "schema_version":
                        if _is_schema_name(v):
                            continue
                        if isinstance(v, ast.Constant) \
                                and isinstance(v.value, int) \
                                and v.value not in schema_versions:
                            if not ctx.suppressed(self.id, v.lineno):
                                yield self.finding(
                                    ctx, v,
                                    f"schema_version {v.value} is not "
                                    f"declared in the wire registry "
                                    f"({reg_rel} declares "
                                    f"{sorted(schema_versions)}) — bump "
                                    f"the registry WITH the surface")
            # -- anchored dicts (router feed, reqlog event) -----------
            if isinstance(node, ast.Dict) and anchors:
                lo = getattr(node, "lineno", 0)
                for const, lines in sorted(anchors.items()):
                    keys = consts.get(const)
                    if keys is None \
                            or not any(lo - 3 <= a <= lo for a in lines):
                        continue
                    lits = [k.value for k in node.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)]
                    extra = sorted(set(lits) - set(keys))
                    missing = sorted(set(keys) - set(lits))
                    if (extra or missing) and not ctx.suppressed(
                            self.id, node.lineno,
                            ctx.node_extent(node)):
                        detail = []
                        if extra:
                            detail.append(f"emits undeclared {extra}")
                        if missing:
                            detail.append(
                                f"misses declared {missing}")
                        yield self.finding(
                            ctx, node,
                            f"anchored keys drifted from {const} "
                            f"({reg_rel}): " + "; ".join(detail)
                            + " — the surface is accrete-only wire, "
                              "register the change first")
            # -- rpc frame shapes -------------------------------------
            if not speaks_rpc or frame_min is None or frame_max is None:
                continue
            if isinstance(node, ast.Tuple) and len(node.elts) >= 2 \
                    and isinstance(node.elts[0], ast.Name) \
                    and node.elts[0].id == "fn" \
                    and isinstance(node.elts[1], ast.Name) \
                    and node.elts[1].id == "args":
                n = len(node.elts)
                if not (frame_min <= n <= frame_max) \
                        and not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"rpc frame tuple has {n} fields; the registry "
                        f"({reg_rel}) declares "
                        f"[{frame_min}, {frame_max}] — growing the "
                        f"frame means bumping RPC_FRAME_MAX first so "
                        f"version skew stays a lint conversation")
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.slice, ast.Slice) \
                    and node.slice.lower is None \
                    and node.slice.upper is not None \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "msg":
                k = self._int_of(node.slice.upper, consts)
                if k is not None and k != frame_min \
                        and not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"rpc frame mandatory-field slice cuts {k} "
                        f"fields; RPC_FRAME_MIN is {frame_min} "
                        f"({reg_rel}) — a wider mandatory slice "
                        f"breaks every legacy client")
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Gt, ast.GtE)) \
                    and isinstance(node.left, ast.Call) \
                    and isinstance(node.left.func, ast.Name) \
                    and node.left.func.id == "len" \
                    and node.left.args \
                    and isinstance(node.left.args[0], ast.Name) \
                    and node.left.args[0].id == "msg":
                k = self._int_of(node.comparators[0], consts)
                thresh = k if isinstance(node.ops[0], ast.Gt) else \
                    (None if k is None else k - 1)
                if thresh is not None and not (
                        frame_min <= thresh < frame_max) \
                        and not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"optional-field probe reads past the declared "
                        f"frame ([{frame_min}, {frame_max}] in "
                        f"{reg_rel}) — the field it guards does not "
                        f"exist on any registered frame")

    @staticmethod
    def _int_of(expr, consts):
        """Int literal, or a Name/Attribute resolving into the registry
        constants; None when neither."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
            return expr.value
        name = None
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        if name in consts and isinstance(consts[name], int):
            return consts[name]
        return None
