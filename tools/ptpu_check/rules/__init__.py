"""Rule registry.  Each module registers one rule class; ALL_RULES is
the ordered public list (order = report order, ids are stable API)."""
from __future__ import annotations

from . import (determinism, donation, excepts, host_sync, locks, metrics,
               wallclock)

ALL_RULES = [
    excepts.SilentExceptRule,
    metrics.MetricHygieneRule,
    host_sync.HostSyncRule,
    donation.DonationRule,
    locks.LockDisciplineRule,
    determinism.DeterminismRule,
    wallclock.WallClockRule,
]

RULES_BY_ID = {r.id: r for r in ALL_RULES}
