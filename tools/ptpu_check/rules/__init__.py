"""Rule registry.  Each module registers one rule class; ALL_RULES is
the ordered public list (order = report order, ids are stable API)."""
from __future__ import annotations

from . import (blocking, determinism, donation, env_flags, excepts,
               host_sync, locks, metrics, recompile, resource_leak,
               wallclock, wire_compat)

ALL_RULES = [
    excepts.SilentExceptRule,
    metrics.MetricHygieneRule,
    host_sync.HostSyncRule,
    donation.DonationRule,
    locks.LockDisciplineRule,
    determinism.DeterminismRule,
    wallclock.WallClockRule,
    resource_leak.ResourceLeakRule,
    blocking.BlockingInHandlerRule,
    recompile.RecompileHazardRule,
    wire_compat.WireCompatRule,
    env_flags.EnvFlagDriftRule,
]

RULES_BY_ID = {r.id: r for r in ALL_RULES}
