"""lock-discipline — state written both under and outside its guard,
and inconsistent two-lock acquisition order.

The bug class, three times over: the TCPStore client reconnect mutated
the shared socket outside the client lock (PR-3 review: concurrent
heartbeat+get raced mutual socket teardown); ``perf._totals`` was
incremented outside ``_rec_lock`` (PR-6 review: two perf-on threads
lost updates and drifted the overall MFU gauge); the async-save
completion event was set outside the condition guarding the pending
count (PR-3 review: wait_until_finished returned with a save pending).

Per class: inventory ``self.X = threading.Lock()/RLock()/Condition()``
attributes; any ``self.Y`` attribute written somewhere under ``with
self.X:`` and ALSO written with no lock held (outside ``__init__``)
flags the unguarded write.  Per module: same for module-level locks
guarding module globals.  Additionally, ``with A: with B:`` in one
place and ``with B: with A:`` in another flags both (deadlock order).

Suppress with ``# ptpu-check[lock-discipline]: why`` (e.g. the write
happens before the object is published to other threads).
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name
from ..core import Rule

LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}


def _is_lock_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    return bool(dn) and dn.rsplit(".", 1)[-1] in LOCK_TYPES


def _lock_id(expr):
    """Stable id for a lock expression we track: `self.X` or a bare
    module-level Name."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return f"self.{expr.attr}"
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class _Write:
    __slots__ = ("attr", "line", "locks", "method", "direct")

    def __init__(self, attr, line, locks, method, direct):
        self.attr = attr
        self.line = line
        self.locks = locks
        self.method = method
        self.direct = direct   # plain `name = ...` vs `name[k] = ...`


def _scan_writes(func_node, lock_names, method_name, writes, pairs):
    """Walk one function recording attribute/global writes with the set
    of tracked locks held, plus nested lock-acquisition order pairs."""

    def visit(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                lid = _lock_id(item.context_expr)
                if lid is not None and lid in lock_names:
                    for outer in new_held:
                        pairs.append((outer, lid, node.lineno))
                    new_held = new_held + (lid,)
            for stmt in node.body:
                visit(stmt, new_held)
            return
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            _record_target(t, node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    def _record_target(t, node, held):
        # self.Y = ... / self.Y[k] = ... / GLOBAL = ... / GLOBAL[k] = ...
        direct = not isinstance(t, ast.Subscript)
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self":
            writes.append(_Write(f"self.{base.attr}", node.lineno,
                                 frozenset(held), method_name, direct))
        elif isinstance(base, ast.Name):
            writes.append(_Write(base.id, node.lineno, frozenset(held),
                                 method_name, direct))

    for stmt in func_node.body:
        visit(stmt, ())
    return writes


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    doc = ("attributes guarded by a lock somewhere must be guarded "
           "everywhere; two-lock order must be consistent")
    descends_from = ("PR-3/PR-6 reviews: store reconnect outside the "
                     "client lock, perf._totals outside _rec_lock, the "
                     "async-save event set outside its condition")

    def check(self, ctx, project):
        # ---- module-level locks guarding module globals -----------------
        mod_locks, mod_globals = set(), set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if _is_lock_ctor(node.value):
                    mod_locks.add(name)
                else:
                    mod_globals.add(name)
        mod_writes, pairs = [], []
        top_funcs = [n for n in ast.walk(ctx.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and n.col_offset == 0]
        for fn in top_funcs:
            writes = _scan_writes(fn, mod_locks, fn.name, [], pairs)
            # a bare-name write is only a GLOBAL write when the function
            # says `global name`; a `name[k] = ...` mutation counts when
            # the function never binds `name` locally (no shadowing)
            gdecl = {n for node in ast.walk(fn)
                     if isinstance(node, ast.Global) for n in node.names}
            local_binds = {w.attr for w in writes
                           if w.direct and w.attr not in gdecl}
            for w in writes:
                if w.attr not in mod_globals:
                    continue
                if w.direct and w.attr not in gdecl:
                    continue
                if not w.direct and w.attr in local_binds:
                    continue
                mod_writes.append(w)
        yield from self._flag_mixed(ctx, mod_writes, scope="module",
                                    init_name=None)
        yield from self._flag_order(ctx, pairs, scope=ctx.rel)

        # ---- per-class locks guarding instance attributes ---------------
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = set()
            methods = [m for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for m in methods:
                for n in ast.walk(m):
                    if isinstance(n, ast.Assign) and \
                            _is_lock_ctor(n.value):
                        for t in n.targets:
                            lid = _lock_id(t)
                            if lid:
                                lock_attrs.add(lid)
            if not lock_attrs:
                continue
            writes, pairs = [], []
            for m in methods:
                _scan_writes(m, lock_attrs, m.name, writes, pairs)
            attr_writes = [w for w in writes
                           if w.attr.startswith("self.")
                           and w.attr not in lock_attrs]
            yield from self._flag_mixed(ctx, attr_writes, scope=cls.name,
                                        init_name="__init__")
            yield from self._flag_order(ctx, pairs,
                                        scope=f"{ctx.rel}:{cls.name}")

    def _flag_mixed(self, ctx, writes, scope, init_name):
        by_attr = {}
        for w in writes:
            by_attr.setdefault(w.attr, []).append(w)
        for attr, ws in sorted(by_attr.items()):
            guards = {l for w in ws for l in w.locks}
            if not guards:
                continue
            unguarded = [w for w in ws if not w.locks
                         and w.method != init_name]
            for w in sorted(unguarded, key=lambda w: w.line):
                if ctx.suppressed(self.id, w.line):
                    continue
                yield self.finding(
                    ctx, _At(w.line),
                    f"`{attr}` is written under "
                    f"`{'`/`'.join(sorted(guards))}` elsewhere but "
                    f"written here (in `{w.method}`) with no lock held "
                    "— racing writers lose updates (the perf._totals/"
                    "store-reconnect class)")

    def _flag_order(self, ctx, pairs, scope):
        seen = {}
        for outer, inner, line in pairs:
            seen.setdefault((outer, inner), []).append(line)
        for (a, b), lines in sorted(seen.items()):
            if (b, a) in seen and a < b:
                l1, l2 = lines[0], seen[(b, a)][0]
                for line, first, second in ((l1, a, b), (l2, b, a)):
                    if ctx.suppressed(self.id, line):
                        continue
                    yield self.finding(
                        ctx, _At(line),
                        f"`{first}` -> `{second}` here but the reverse "
                        f"order is taken at line "
                        f"{l2 if line == l1 else l1} — inconsistent "
                        "two-lock order deadlocks under contention")


class _At:
    """Line-only anchor for findings not tied to one AST node."""

    def __init__(self, line):
        self.lineno = line
        self.col_offset = 0
