"""donation — reads of a buffer after it was donated to a jitted call.

The bug class (PR 3): ``jax.jit(fn, donate_argnums=...)`` invalidates
the caller's argument buffers — a later read of the same Python name
sees a deleted/garbage array ("donated buffer" errors on TPU, silent
stale data in some CPU paths).  StepGuard's pre-step snapshots had to
COPY arrays for exactly this reason: the optimizer's donating jitted
update invalidated reference-only snapshots.

Local (per-function) dataflow, statements in source order:

- a name bound to ``jax.jit(fn, donate_argnums=(...))`` (literal
  positions) marks its donated call-arguments — LOCAL bindings,
  MODULE-LEVEL bindings (``_update = jax.jit(...)`` at top level, the
  engine idiom), and bindings via a HELPER that returns a donating jit
  call (``update = make_update()``; the helper resolves through the
  call graph, cross-file included) all count;
- ``from jax import jit as J`` aliases resolve (v1 only matched dotted
  ``*.jit`` names);
- class methods decorated ``@partial(jax.jit, static_argnums=(0,),
  donate_argnums=...)`` donate the corresponding caller positions of
  ``self.method(...)`` calls (self-offset applied);
- any later Load of a donated name in the same function flags; a Store
  re-binding the name (the standard ``state = update(state, ...)``
  shape) clears it.

Suppress with ``# ptpu-check[donation]: why`` (e.g. the read is
dead-code-eliminated under jit, or the call path copies first).
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name
from ..core import Rule


def _literal_positions(kw_value):
    """donate_argnums=(1, 3) / [1] / 2 -> tuple of ints, else None."""
    if isinstance(kw_value, ast.Constant) and isinstance(kw_value.value,
                                                         int):
        return (kw_value.value,)
    if isinstance(kw_value, (ast.Tuple, ast.List)):
        out = []
        for e in kw_value.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _donating_jit_call(node, idx=None):
    """Call expr `jax.jit(f, donate_argnums=...)` -> positions or None.
    With a ModuleIndex, `from jax import jit as J` aliases resolve."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn is None:
        return None
    if dn.rsplit(".", 1)[-1] not in ("jit", "pjit"):
        if idx is None or "." in dn \
                or idx.sym_import.get(dn, ("",))[0] != "jax" \
                or idx.sym_import[dn][1] not in ("jit", "pjit"):
            return None
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            return _literal_positions(kw.value)
    return None


def _donating_returns(project):
    """{func key: positions} for functions whose return value is a
    donating jit call — a caller binding that helper's result holds a
    donating callable (`update = make_update()`).  Cached."""
    cached = getattr(project, "_donation_returns", None)
    if cached is not None:
        return cached
    cg = project.callgraph
    out = {}
    for fi in cg.functions.values():
        idx = cg.index_of(fi.rel)
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Return):
                pos = _donating_jit_call(n.value, idx)
                if pos:
                    out[fi.key] = pos
    project._donation_returns = out
    return out


def _method_donations(cls_node):
    """{method name: donated positions (def-indexed, incl. self)} for
    methods decorated with a donating jit/partial(jit, ...)."""
    out = {}
    for item in cls_node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in item.decorator_list:
            pos = _donating_jit_call(dec)
            if pos is None and isinstance(dec, ast.Call):
                # functools.partial(jax.jit, ..., donate_argnums=...)
                dn = dotted_name(dec.func)
                if dn and dn.rsplit(".", 1)[-1] == "partial" and dec.args:
                    inner = dotted_name(dec.args[0])
                    if inner and inner.rsplit(".", 1)[-1] in ("jit",
                                                              "pjit"):
                        for kw in dec.keywords:
                            if kw.arg == "donate_argnums":
                                pos = _literal_positions(kw.value)
            if pos:
                out[item.name] = pos
    return out


class _FuncScan:
    """Source-order walk of ONE function body tracking donated names."""

    def __init__(self, rule, ctx, method_donations, module_jitted=None,
                 resolver=None, idx=None):
        self.rule = rule
        self.ctx = ctx
        self.method_donations = method_donations
        self.module_jitted = module_jitted or {}
        self.resolver = resolver    # Call node -> positions (helpers)
        self.idx = idx
        self.jitted = {}     # local name -> donated positions
        self.donated = {}    # name -> line it was donated at
        self.findings = []

    def run(self, func_node):
        for stmt in func_node.body:
            self.visit(stmt)
        return self.findings

    def visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return   # nested defs are their own scope
        if isinstance(node, ast.Assign):
            self.visit(node.value)
            pos = _donating_jit_call(node.value, self.idx)
            if pos is None and self.resolver is not None:
                pos = self.resolver(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if pos:
                        self.jitted[t.id] = pos
                    else:
                        self.jitted.pop(t.id, None)
                    self.donated.pop(t.id, None)
                else:
                    self.visit(t)
            return
        if isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                self._load(node.target)
            self.visit(node.value)
            if isinstance(node.target, ast.Name):
                self.donated.pop(node.target.id, None)
            return
        if isinstance(node, ast.Call):
            self.visit(node.func)
            positions = self._call_donates(node)
            for a in node.args:
                self.visit(a)
            for k in node.keywords:
                self.visit(k.value)
            if positions:
                for p in positions:
                    if 0 <= p < len(node.args) and \
                            isinstance(node.args[p], ast.Name):
                        self.donated[node.args[p].id] = node.lineno
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self._load(node)
            else:
                self.donated.pop(node.id, None)
                self.jitted.pop(node.id, None)
            return
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _call_donates(self, node):
        """Donated CALL-ARG indices for this call, or None."""
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in self.jitted:
                return self.jitted[f.id]
            if f.id in self.module_jitted:   # top-level binding
                return self.module_jitted[f.id]
        direct = _donating_jit_call(f, self.idx)  # jax.jit(g, ...)()
        if direct:
            return direct
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and f.attr in self.method_donations:
            # def-indexed positions include self at 0 -> call index - 1
            return tuple(p - 1 for p in self.method_donations[f.attr]
                         if p >= 1)
        return None

    def _load(self, name_node):
        line = self.donated.pop(name_node.id, None)
        if line is not None and not self.ctx.suppressed(
                self.rule.id, name_node.lineno):
            self.findings.append(self.rule.finding(
                self.ctx, name_node,
                f"`{name_node.id}` is read after being donated to the "
                f"jitted call on line {line} — the buffer is invalidated"
                " by donation; copy before donating or re-bind the "
                "result (the PR-3 snapshot bug)"))


class DonationRule(Rule):
    id = "donation"
    doc = "no reads of a name after it was passed to a donating jit call"
    descends_from = ("PR-3: StepGuard snapshots held references the "
                     "optimizer's donate_argnums update invalidated — "
                     "restore restored garbage until snapshots copied")

    def check(self, ctx, project):
        cg = project.callgraph
        idx = cg.index_of(ctx.rel)
        helper_returns = _donating_returns(project)
        # class-level inventory of donating methods (per enclosing class)
        class_methods = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                class_methods[node] = _method_donations(node)
        # module-level donating bindings (`_update = jax.jit(f, ...)`)
        module_jitted = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = _donating_jit_call(node.value, idx)
                if pos:
                    module_jitted[node.targets[0].id] = pos

        def scan(owner_cls, func_node):
            md = class_methods.get(owner_cls, {})
            fi = cg._by_node.get(id(func_node)) if cg is not None \
                else None

            def resolver(call_node):
                # `u = make_update()` — helper returning a donating jit
                if not isinstance(call_node, ast.Call) or idx is None:
                    return None
                tgt = cg.resolve(call_node.func, idx, fi)
                if tgt is not None:
                    return helper_returns.get(tgt.key)
                return None

            yield from _FuncScan(self, ctx, md, module_jitted,
                                 resolver, idx).run(func_node)

        def visit(node, owner_cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    yield from visit(child, child)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    yield from scan(owner_cls, child)
                    yield from visit(child, None)
                else:
                    yield from visit(child, owner_cls)

        yield from visit(ctx.tree, None)
