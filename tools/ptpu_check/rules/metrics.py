"""metric-hygiene — monitor metric naming + label cardinality.

Re-homed from ``tools/lint_metrics.py`` (PR 5).  Metric names must be
LITERAL ``subsystem/metric_name`` strings (dynamic names hide from grep
and from this lint); ``.labels()`` takes explicit keywords only, at
most MAX_LABELS of them (every key multiplies series cardinality).

Suppress with ``ptpu-check[metric-hygiene]: why`` (or the legacy
``metric-ok:`` comment tag) on the line or the line above.
"""
from __future__ import annotations

import ast
import re

from ..core import Rule

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)+$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")
MAX_LABELS = 3
METRIC_METHODS = ("counter", "gauge", "histogram")
REGISTRY_NAMES = ("monitor", "m", "_monitor")
SKIP_FILES = ("paddle_tpu/monitor/__init__.py",)   # the registry itself


def _is_metric_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in METRIC_METHODS:
        return False
    v = f.value
    if isinstance(v, ast.Name) and v.id in REGISTRY_NAMES:
        return True
    if isinstance(v, ast.Attribute) and v.attr == "monitor":
        return True
    return False


class MetricHygieneRule(Rule):
    id = "metric-hygiene"
    doc = ("metric names are literal `subsystem/metric`; .labels() is "
           "keyword-only and bounded")
    descends_from = ("PR-5 audit: f-string metric names (ops/lowbit) and "
                     "`.labels(**lab)` (pipeline) hid series from "
                     "dashboards and unbounded their cardinality")

    def check(self, ctx, project):
        if ctx.rel in SKIP_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if _is_metric_call(node):
                if ctx.suppressed(self.id, node.lineno):
                    continue
                if not node.args:
                    yield self.finding(ctx, node,
                                       f"{f.attr}() without a metric name")
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    if not NAME_RE.match(arg.value):
                        yield self.finding(
                            ctx, node,
                            f"metric name {arg.value!r} breaks the "
                            "`subsystem/metric_name` convention "
                            f"({NAME_RE.pattern})")
                else:
                    yield self.finding(
                        ctx, node,
                        f"dynamic metric name in {f.attr}() — pass a "
                        "literal `subsystem/metric`, or document the "
                        "helper with `# ptpu-check[metric-hygiene]: ...`")
            elif isinstance(f, ast.Attribute) and f.attr == "labels":
                if ctx.suppressed(self.id, node.lineno):
                    continue
                if node.args:
                    yield self.finding(
                        ctx, node,
                        ".labels() takes keywords only "
                        "(labels(kind=...), not labels(value))")
                kws = node.keywords
                if any(k.arg is None for k in kws):
                    yield self.finding(
                        ctx, node,
                        ".labels(**dict) hides the label set — spell the "
                        "keywords out, or document with "
                        "`# ptpu-check[metric-hygiene]: ...`")
                if len(kws) > MAX_LABELS:
                    yield self.finding(
                        ctx, node,
                        f".labels() with {len(kws)} keys (> {MAX_LABELS}):"
                        " every key multiplies series cardinality")
                for k in kws:
                    if k.arg is not None and not LABEL_RE.match(k.arg):
                        yield self.finding(
                            ctx, node,
                            f"label key {k.arg!r} breaks "
                            f"{LABEL_RE.pattern}")
