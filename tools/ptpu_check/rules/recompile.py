"""recompile-hazard — per-call-varying Python scalars flowing into
shapes or jit static positions: the STATIC twin of the PR-10 runtime
``jit/recompile_cause`` explainer.

The bug class: PR-2's engine re-specialized its decode program on every
batch-size crossing because host code built device arrays whose shapes
came from ``len(rows)``; PR-7 killed that class at the engine level
with ONE fixed-shape ragged program, and PR-10 landed the runtime
explainer that names the varying axis AFTER the storm hits.  This rule
names the hazard before merge instead:

- **varying shape construction**: ``jnp.zeros(n, ...)`` /
  ``np.empty((b, s))`` / ``full``/``ones``/``arange`` where the shape
  expression derives from a per-call-varying PYTHON scalar —
  ``len(...)`` of a non-constant container, or a local name bound from
  one — inside a HOST function that drives tracing (contains a
  jit-family call, calls a jitted callable, or transitively reaches a
  function that does).  Every distinct value compiles a fresh program.
- **varying static position**: a call of a name bound to ``jax.jit(f,
  static_argnums=(...))`` (local or module-level binding, and
  ``@partial(jax.jit, static_argnums=...)`` methods via
  ``self.m(...)``) passing a ``len(...)``-derived or
  ``.shape``-derived scalar in a static position — each distinct value
  is a cache miss by definition.

Deliberately NOT varying sources, to keep the signal honest:

- a ``.shape`` read of an existing array, OUTSIDE static positions —
  the array's shape already specializes every program it feeds, so a
  ``jnp.zeros(x.shape[0])`` adds no recompile axis the input didn't;
- anything inside a TRACED function (``cg.traced``): there ``len()``/
  ``.shape`` are static at trace time by construction, and host
  concretization inside traced code is ``host-sync``'s finding, not
  this rule's.

Deliberate bounded specialization (the engine's power-of-2 bucketing,
pad-to-fixed shapes) is exactly what the suppression marker is for:
``# ptpu-check[recompile-hazard]: bucketed — bounded program count``.
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name, iter_body_nodes
from ..core import Rule

SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange"}
ARRAY_MODULES = {"jax.numpy", "numpy"}


def _trace_drivers(project):
    """{func key: description} for functions that drive tracing —
    contain a jit-family call / call a jitted binding — plus every
    function that transitively reaches one (reverse closure).  Cached
    on the project."""
    cached = getattr(project, "_recompile_drivers", None)
    if cached is not None:
        return cached
    cg = project.callgraph
    seeds = {}
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        idx = cg.index_of(ctx.rel)
        if idx is None:
            continue
        jit_bound = _jit_bound_names(ctx)
        for fi in [f for f in cg.functions.values()
                   if f.rel == ctx.rel]:
            for n in iter_body_nodes(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                if cg.is_jit_entry_callable(n.func, idx):
                    seeds.setdefault(
                        fi.key, f"contains a "
                        f"`{dotted_name(n.func)}` call at "
                        f"{ctx.rel}:{n.lineno}")
                    break
                f = n.func
                name = f.id if isinstance(f, ast.Name) else None
                if name and name in jit_bound:
                    seeds.setdefault(
                        fi.key, f"dispatches the jitted "
                        f"`{name}` at {ctx.rel}:{n.lineno}")
                    break
    # reverse closure: callers of drivers drive tracing too
    redges = cg._reverse_edges()
    out = dict(seeds)
    work = list(seeds)
    while work:
        k = work.pop()
        origin = out[k]
        for caller in redges.get(k, ()):
            if caller not in out:
                out[caller] = origin
                work.append(caller)
    project._recompile_drivers = out
    return out


def _jit_bound_names(ctx):
    """Module-level and local names bound to jit-family call results
    (``_exec = jax.jit(f)``), plus their static_argnums when literal:
    {name: tuple-or-None}."""
    out = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Call):
            dn = dotted_name(n.value.func)
            if dn and dn.rsplit(".", 1)[-1] in ("jit", "pjit"):
                static = None
                for kw in n.value.keywords:
                    if kw.arg == "static_argnums":
                        static = _literal_ints(kw.value)
                out[n.targets[0].id] = static
    return out


def _literal_ints(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _method_statics(ctx):
    """{method name: static positions} for @partial(jax.jit,
    static_argnums=...) methods (def-indexed, incl. self)."""
    out = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in meth.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dn = dotted_name(dec.func) or ""
                if dn.rsplit(".", 1)[-1] != "partial" or not dec.args:
                    continue
                inner = dotted_name(dec.args[0]) or ""
                if inner.rsplit(".", 1)[-1] not in ("jit", "pjit"):
                    continue
                for kw in dec.keywords:
                    if kw.arg == "static_argnums":
                        pos = _literal_ints(kw.value)
                        if pos:
                            out[meth.name] = pos
    return out


class _VaryTracker:
    """Per-function: which local names hold per-call-varying scalars
    (len() results, .shape-derived values)."""

    def __init__(self, array_aliases=()):
        self.varying = {}        # name -> short reason (len-derived)
        self.shape_derived = {}  # name -> reason (.shape-derived)
        self.arrays = set()      # names bound from np./jnp. calls —
        #                          len(array) ≡ array.shape[0], which is
        #                          shape-following, not a new axis
        self.array_aliases = set(array_aliases)

    def scan(self, func_node):
        nodes = sorted(iter_body_nodes(func_node),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        for n in nodes:
            if isinstance(n, ast.Assign):
                if isinstance(n.value, ast.Call):
                    dn = dotted_name(n.value.func) or ""
                    if dn.split(".", 1)[0] in self.array_aliases:
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                self.arrays.add(t.id)
                why = self.vary_reason(n.value)
                shape_why = why or self.vary_reason(n.value,
                                                    with_shape=True)
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        if why:
                            self.varying[t.id] = why
                        else:
                            self.varying.pop(t.id, None)
                        if shape_why:
                            self.shape_derived[t.id] = shape_why
                        else:
                            self.shape_derived.pop(t.id, None)
                    elif isinstance(t, ast.Tuple) and _is_shape(
                            n.value):
                        for e in t.elts:
                            if isinstance(e, ast.Name):
                                self.shape_derived[e.id] = \
                                    "unpacked from `.shape`"
        return self

    def vary_reason(self, expr, with_shape=False):
        """Why `expr` varies per call, or None.  `.shape`-derived
        scalars count only when `with_shape` (static positions): an
        existing array's shape already specializes every program it
        feeds, so deriving a SHAPE from it adds no recompile axis —
        but feeding it into a STATIC position turns a would-be traced
        axis into a compile key."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "len" and n.args \
                    and not isinstance(n.args[0], ast.Constant) \
                    and not (isinstance(n.args[0], ast.Name)
                             and n.args[0].id in self.arrays):
                return "a `len(...)` of a per-call container"
            if isinstance(n, ast.Name) and n.id in self.varying:
                return self.varying[n.id]
            if with_shape:
                if isinstance(n, ast.Subscript) and _is_shape(n.value):
                    return "a `.shape[...]` scalar"
                if isinstance(n, ast.Name) and n.id in self.shape_derived:
                    return self.shape_derived[n.id]
        return None


def _is_shape(node):
    return isinstance(node, ast.Attribute) and node.attr == "shape"


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    doc = ("no per-call-varying scalars (len()/unpacked .shape) into "
           "shape constructors or jit static positions in "
           "trace-driving code")
    descends_from = ("PR-2: decode shapes from len(rows) recompiled "
                     "every batch crossing until PR-7's fixed-shape "
                     "ragged program; PR-10 built the runtime "
                     "recompile_cause explainer this rule is the "
                     "static twin of")

    def check(self, ctx, project):
        drivers = _trace_drivers(project)
        if not any(k[0] == ctx.rel for k in drivers):
            return
        cg = project.callgraph
        idx = cg.index_of(ctx.rel)
        array_aliases = {name for name, mod in idx.mod_alias.items()
                         if mod in ARRAY_MODULES}
        array_aliases |= {name for name, (m, s) in
                          idx.sym_import.items()
                          if (m, s) == ("jax", "numpy")}
        jit_bound = _jit_bound_names(ctx)
        meth_statics = _method_statics(ctx)
        for key, why_driver in sorted(drivers.items()):
            if key[0] != ctx.rel:
                continue
            if key in cg.traced:
                # inside traced code len()/.shape are static at trace
                # time — concretization there is host-sync's finding
                continue
            fi = cg.functions[key]
            tracker = _VaryTracker(array_aliases).scan(fi.node)
            where = (f"`{fi.qualname}` drives tracing "
                     f"({why_driver})")
            for n in iter_body_nodes(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dn = dotted_name(n.func) or ""
                parts = dn.split(".")
                if len(parts) >= 2 and parts[0] in array_aliases \
                        and parts[-1] in SHAPE_CTORS and n.args:
                    why = tracker.vary_reason(n.args[0])
                    # extent: a trailing marker on ANY physical line of
                    # a multi-line allocation counts
                    if why and not ctx.suppressed(
                            self.id, n.lineno,
                            getattr(n, "end_lineno", n.lineno)):
                        yield self.finding(
                            ctx, n,
                            f"`{dn}(...)` builds a shape from {why} "
                            f"— every distinct value compiles a "
                            f"fresh program (the PR-2 recompile-"
                            f"storm class; pad to a fixed bucket or "
                            f"justify the bounded specialization); "
                            f"{where}")
                        continue
                static = None
                f = n.func
                if isinstance(f, ast.Name) and f.id in jit_bound:
                    static = jit_bound[f.id]
                    offset = 0
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" \
                        and f.attr in meth_statics:
                    static = meth_statics[f.attr]
                    offset = 1   # def positions include self
                if static:
                    for p in static:
                        i = p - offset
                        if 0 <= i < len(n.args):
                            why = tracker.vary_reason(n.args[i],
                                                      with_shape=True)
                            if why and not ctx.suppressed(
                                    self.id, n.lineno,
                                    getattr(n, "end_lineno",
                                            n.lineno)):
                                yield self.finding(
                                    ctx, n,
                                    f"static position {p} of this "
                                    f"jitted call receives {why} — "
                                    f"each distinct value is a "
                                    f"fresh compile by definition "
                                    f"(the jit/recompile_cause "
                                    f"static twin); {where}")
