"""blocking-in-handler — unbounded blocking calls reachable from
contexts that must never block: signal handlers, HTTP handler methods,
daemon loop bodies.

The bug class: PR-5 moved signal-path flight dumps onto a helper thread
because an inline dump could self-deadlock on a lock the interrupted
main thread held; PR-9 found store registration blocking inside
``start_server``'s lock wedged every scrape; PR-10's ``/profile``
endpoint needed single-flight because a second capture would block the
http daemon thread behind the first.  Each was found by hand.  The
multi-replica serving tier multiplies handler surface — this rule walks
the call graph from every handler context and flags unbounded blocking
primitives inside.

Handler contexts (each finding names its entry, like ``host-sync``):

- functions registered via ``signal.signal(sig, fn)`` — plus every
  function they reach;
- ``do_*`` methods on classes whose bases mention
  ``BaseHTTPRequestHandler`` (the stdlib http handler surface);
- functions passed as ``target=`` to a ``threading.Thread(...,
  daemon=True)`` — daemon loop bodies: the process exits WITHOUT
  joining them, so an unbounded block there dies holding whatever it
  holds.

Flagged primitives:

- ``x.acquire()`` with neither a ``timeout=`` nor ``blocking=False`` —
  an unbounded lock wait (``with lock:`` is not flagged: it is the
  pervasive idiom and rewriting it everywhere is not the lesson;
  explicit ``acquire()`` is where the hand-audits kept finding hangs);
- zero-argument ``x.join()`` / ``x.wait()`` / ``x.result()`` /
  ``x.get()`` — unbounded thread/event/future/queue waits;
- ``time.sleep(...)`` in SIGNAL contexts only (a daemon loop's cadence
  sleep is its design; a signal handler sleeping holds the interrupted
  frame hostage).

Suppress with ``# ptpu-check[blocking-in-handler]: why`` — e.g. a
bounded-by-construction wait the analysis cannot see.
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name, iter_body_nodes
from ..core import Rule

UNBOUNDED_ZERO_ARG = {"join": "thread join", "wait": "event/cond wait",
                      "result": "future result", "get": "queue get"}


def _handler_seeds(project):
    """{func key: (context kind, origin description)} for every handler
    entry in the analyzed set.  Cached on the project."""
    cached = getattr(project, "_blocking_seeds", None)
    if cached is not None:
        return cached
    cg = project.callgraph
    seeds = {}
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        idx = cg.index_of(ctx.rel)
        if idx is None:
            continue
        # signal.signal(sig, fn) registrations + daemon Thread targets
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func) or ""
            if dn.rsplit(".", 1)[-1] == "signal" \
                    and ("signal" in dn.split(".", 1)[0]
                         or dn == "signal"):
                if len(node.args) >= 2:
                    tgt = cg.resolve(node.args[1], idx,
                                     _enclosing_func(cg, ctx, node))
                    if tgt is not None:
                        seeds.setdefault(tgt.key, (
                            "signal",
                            f"registered as a signal handler at "
                            f"{ctx.rel}:{node.lineno}"))
            if dn.rsplit(".", 1)[-1] == "Thread":
                target, daemon = None, False
                for k in node.keywords:
                    if k.arg == "target":
                        target = k.value
                    if k.arg == "daemon" \
                            and isinstance(k.value, ast.Constant) \
                            and k.value.value:
                        daemon = True
                if daemon and target is not None:
                    tgt = cg.resolve(target, idx,
                                     _enclosing_func(cg, ctx, node))
                    if tgt is not None:
                        seeds.setdefault(tgt.key, (
                            "daemon",
                            f"daemon-thread loop body (Thread target "
                            f"at {ctx.rel}:{node.lineno})"))
        # do_* methods of http handler classes
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            base_names = [dotted_name(b) or getattr(b, "id", "")
                          for b in node.bases]
            if not any(b and "HTTPRequestHandler" in b
                       for b in base_names):
                continue
            for meth in node.body:
                if isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and meth.name.startswith("do_"):
                    fi = cg._by_node.get(id(meth))
                    if fi is not None:
                        seeds.setdefault(fi.key, (
                            "http",
                            f"http handler `{node.name}.{meth.name}` "
                            f"({ctx.rel}:{meth.lineno})"))
    # BFS: everything a handler context reaches inherits the context
    reach = cg.reachable_from(seeds)
    project._blocking_seeds = reach
    return reach


def _enclosing_func(cg, ctx, node):
    """Best-effort FuncInfo whose body contains `node` (by line range);
    used only to give resolve() a lexical scope."""
    best = None
    for fi in cg.functions.values():
        if fi.rel != ctx.rel:
            continue
        lo = fi.node.lineno
        hi = getattr(fi.node, "end_lineno", lo)
        if lo <= getattr(node, "lineno", 0) <= hi:
            if best is None or fi.node.lineno > best.node.lineno:
                best = fi
    return best


class BlockingInHandlerRule(Rule):
    id = "blocking-in-handler"
    doc = ("no unbounded lock/join/wait/result/get (and no sleep in "
           "signal contexts) reachable from signal handlers, http "
           "handlers, or daemon loop bodies")
    descends_from = ("PR-5: inline flight dumps in signal handlers "
                     "could self-deadlock on the interrupted frame's "
                     "locks; PR-9: store registration blocking inside "
                     "start_server's lock wedged scrapes forever")

    def check(self, ctx, project):
        reach = _handler_seeds(project)
        cg = project.callgraph
        for key, (kind, origin) in sorted(reach.items()):
            if key[0] != ctx.rel:
                continue
            fi = cg.functions[key]
            where = (f"`{fi.qualname}` is reachable from a "
                     f"never-block context ({origin})")
            for n in iter_body_nodes(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                if isinstance(f, ast.Attribute):
                    if f.attr == "acquire":
                        bounded = any(
                            k.arg in ("timeout", "blocking")
                            for k in n.keywords) or n.args
                        if not bounded and not ctx.suppressed(
                                self.id, n.lineno):
                            yield self.finding(
                                ctx, n,
                                f"unbounded `.acquire()` — a held "
                                f"lock wedges this context forever; "
                                f"acquire(timeout=...) and handle the "
                                f"miss; {where}")
                        continue
                    if f.attr in UNBOUNDED_ZERO_ARG and not n.args \
                            and not n.keywords:
                        if not ctx.suppressed(self.id, n.lineno):
                            yield self.finding(
                                ctx, n,
                                f"unbounded `.{f.attr}()` "
                                f"({UNBOUNDED_ZERO_ARG[f.attr]}) — "
                                f"give it a timeout and handle the "
                                f"expiry; {where}")
                        continue
                dn = dotted_name(f)
                if kind == "signal" and dn \
                        and dn.rsplit(".", 1)[-1] == "sleep" \
                        and dn.split(".", 1)[0] == "time":
                    if not ctx.suppressed(self.id, n.lineno):
                        yield self.finding(
                            ctx, n,
                            f"`time.sleep(...)` in a signal context "
                            f"holds the interrupted frame hostage; "
                            f"{where}")
