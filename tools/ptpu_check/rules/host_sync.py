"""host-sync — host syncs hiding in traced/hot code.

The bug class: inside a function that XLA traces (reachable from a
``jax.jit``/``pjit``/``lax`` control-flow/``shard_map`` entry), calling
``np.asarray``/``.item()``/``int()``/``float()``/``bool()`` on a traced
value either errors at trace time or — worse — silently concretizes on
every call, serializing the device stream (the PR-4 observer bug:
``np.asarray`` round-tripped every calibration batch through the host
and errored under jit; the serving engine's decode path had the same
shape).

Reachability comes from the cross-file call graph; each finding names
the jit entry it is reachable from.  Flagged:

- ``.item()`` / ``.tolist()`` / ``.numpy()`` / ``.block_until_ready()``
  method calls;
- ``np.asarray/np.array/...`` host materializations (``np`` = any alias
  of ``numpy``);
- ``jax.device_get(...)``;
- ``int()/float()/bool()`` whose argument is a PARAMETER of the traced
  function (parameters are exactly the traced values) and not an
  obviously-static expression (``.shape``/``len()``/``.ndim``/dtypes);
- ``if``/``while`` tests that CALL a ``jnp.*`` reduction — Python
  branching on a traced value forces a device->host sync per step.

Suppress with ``# ptpu-check[host-sync]: why`` — e.g. for functions
that take the traced-entry path only under ``static_argnums`` configs.
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name, iter_body_nodes
from ..core import Rule

HOST_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
NP_HOST_FNS = {"asarray", "array", "ascontiguousarray", "frombuffer",
               "copyto", "save", "savez", "asnumpy"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}
CASTS = {"int", "float", "bool", "complex"}
# jnp helpers that act on dtypes/shapes — static at trace time, so
# branching on them is fine
STATIC_JNP_HELPERS = {"issubdtype", "result_type", "promote_types",
                      "can_cast", "finfo", "iinfo", "dtype", "isdtype",
                      "ndim", "isscalar"}


def _looks_static(node) -> bool:
    """Expressions whose value is known at trace time (shapes, dtypes,
    literals) — casting THOSE is fine."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in STATIC_ATTRS or _looks_static(node.value)
    if isinstance(node, ast.Subscript):
        return _looks_static(node.value)
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in {"len", "min", "max",
                                                "abs", "round"} | CASTS:
            return all(_looks_static(a) for a in node.args)
        if isinstance(f, ast.Attribute) and f.attr in {"count", "index"}:
            return True
        return False
    if isinstance(node, ast.BinOp):
        return _looks_static(node.left) and _looks_static(node.right)
    if isinstance(node, ast.UnaryOp):
        return _looks_static(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_looks_static(e) for e in node.elts)
    return False


def _contains_param(node, params) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in params:
            return True
    return False


class HostSyncRule(Rule):
    id = "host-sync"
    doc = ("no np.asarray/.item()/int()/jnp-branching on traced values "
           "in functions reachable from jit entries")
    descends_from = ("PR-4: AbsmaxObserver np.asarray host-synced every "
                     "calibration batch and errored under jit; the "
                     "serving engine's early decode path had int()-on-"
                     "traced host syncs")

    def check(self, ctx, project):
        cg = project.callgraph
        idx = cg.index_of(ctx.rel)
        if idx is None:
            return
        jnp_aliases = {name for name, mod in idx.mod_alias.items()
                       if mod == "jax.numpy"}
        jnp_aliases |= {name for name, (mod, sym) in idx.sym_import.items()
                        if (mod, sym) == ("jax", "numpy")}
        np_aliases = {name for name, mod in idx.mod_alias.items()
                      if mod == "numpy"}
        for fi, origin in cg.traced_functions_in(ctx.rel):
            params = {a.arg for a in (
                fi.node.args.posonlyargs + fi.node.args.args
                + fi.node.args.kwonlyargs)} - {"self", "cls"}
            where = (f"`{fi.qualname}` is reachable from a trace entry "
                     f"({origin})")
            for n in iter_body_nodes(fi.node):
                if isinstance(n, ast.Call):
                    for found in self._check_call(ctx, n, params,
                                                  np_aliases, where):
                        yield found
                elif isinstance(n, (ast.If, ast.While)):
                    test = n.test
                    for sub in ast.walk(test):
                        if isinstance(sub, ast.Call):
                            dn = dotted_name(sub.func)
                            if dn and dn.split(".")[0] in jnp_aliases \
                                    and dn.rsplit(".", 1)[-1] not in \
                                    STATIC_JNP_HELPERS:
                                if not ctx.suppressed(self.id, n.lineno):
                                    yield self.finding(
                                        ctx, n,
                                        f"Python `{type(n).__name__.lower()}`"
                                        f" branches on `{dn}(...)` — "
                                        "concretizing a traced value forces "
                                        "a device->host sync (or a trace "
                                        f"error); {where}")
                                break

    def _check_call(self, ctx, n, params, np_aliases, where):
        f = n.func
        if isinstance(f, ast.Attribute):
            if f.attr in HOST_METHODS and not n.args:
                if not ctx.suppressed(self.id, n.lineno):
                    yield self.finding(
                        ctx, n,
                        f"`.{f.attr}()` in traced code materializes on "
                        f"the host; {where}")
                return
            base = f.value
            if isinstance(base, ast.Name) and base.id in np_aliases \
                    and f.attr in NP_HOST_FNS:
                if n.args and _looks_static(n.args[0]):
                    return
                if not ctx.suppressed(self.id, n.lineno):
                    yield self.finding(
                        ctx, n,
                        f"`{base.id}.{f.attr}(...)` in traced code pulls "
                        f"the value to the host; {where}")
                return
            dn = dotted_name(f)
            if dn and dn.endswith("device_get"):
                if not ctx.suppressed(self.id, n.lineno):
                    yield self.finding(
                        ctx, n,
                        f"`{dn}(...)` in traced code; {where}")
                return
        elif isinstance(f, ast.Name) and f.id in CASTS:
            if len(n.args) == 1 and not _looks_static(n.args[0]) \
                    and _contains_param(n.args[0], params):
                if not ctx.suppressed(self.id, n.lineno):
                    yield self.finding(
                        ctx, n,
                        f"`{f.id}(...)` on a traced argument concretizes "
                        f"it on the host; {where}")
