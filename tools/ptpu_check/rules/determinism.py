"""determinism — hash-order iteration and global-RNG draws in library
code.

The bug class: PR 2's gradient-merge parity flake was PYTHONHASHSEED-
dependent state-threading corruption — ``_select_tree`` merged slot
dicts via ``set(a) | set(b)`` and iterated the union, so the compiled
program's state order changed per process; and PR 3's retry jitter
originally drew from the GLOBAL ``random`` stream, shifting every
seeded ``reader.shuffle`` sequence that ran after a retry.

Flagged:

- iteration over a set-typed expression (``set(...)`` calls, set
  literals/comprehensions, ``|``/``&``/``-``/``^`` unions of them,
  ``.union(...)`` etc.) in a ``for``, a comprehension, or a
  ``list()/tuple()/enumerate()/iter()/join()`` call — UNLESS wrapped in
  ``sorted(...)``.  Local names bound to a set expression and then
  iterated are tracked within the function;
- calls on the process-global RNG streams — ``random.<draw>()`` /
  ``np.random.<draw>()`` — in ``paddle_tpu/`` library code (instance
  RNGs ``random.Random(seed)`` / ``np.random.default_rng`` /
  ``RandomState`` are the fix and are not flagged).

Suppress with ``# ptpu-check[determinism]: why`` (e.g. order provably
does not reach program/signature construction, or global-stream
semantics are the documented paddle-compat contract).
"""
from __future__ import annotations

import ast

from ..core import Rule

ITER_CALLS = {"list", "tuple", "enumerate", "iter", "next", "reversed"}
SET_METHODS = {"union", "intersection", "difference",
               "symmetric_difference"}
RNG_SAFE = {"Random", "SystemRandom", "getstate", "setstate",
            "default_rng", "RandomState", "Generator", "get_state",
            "set_state", "seed"}
SET_OPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)


def _is_set_expr(node, set_names) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        if isinstance(f, ast.Attribute) and f.attr in SET_METHODS:
            return _is_set_expr(f.value, set_names)
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, SET_OPS):
        return (_is_set_expr(node.left, set_names)
                or _is_set_expr(node.right, set_names))
    if isinstance(node, ast.Name):
        return node.id in set_names
    return False


class DeterminismRule(Rule):
    id = "determinism"
    doc = ("no iteration over unordered sets feeding downstream state, "
           "no global-RNG draws in library code")
    descends_from = ("PR-2: `set(a) | set(b)` iteration made jit state "
                     "threading PYTHONHASHSEED-dependent (compiled-vs-"
                     "eager gradient-merge corruption); PR-3: retry "
                     "jitter on the global `random` stream shifted "
                     "seeded reader.shuffle sequences")

    def check(self, ctx, project):
        idx = project.callgraph.index_of(ctx.rel)
        rng_aliases = set()
        nprng_bases = set()
        if idx is not None:
            rng_aliases = {n for n, mod in idx.mod_alias.items()
                           if mod == "random"}
            nprng_bases = {n for n, mod in idx.mod_alias.items()
                           if mod == "numpy"}
            nprng_bases |= {n for n, mod in idx.mod_alias.items()
                            if mod == "numpy.random"}

        # ---- set-order iteration (function-scoped name tracking) --------
        def scan_scope(body, set_names):
            for stmt in body:
                yield from visit(stmt, set_names)

        def visit(node, set_names):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from scan_scope(node.body, set())
                return
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                if _is_set_expr(node.value, set_names):
                    set_names.add(node.targets[0].id)
                else:
                    set_names.discard(node.targets[0].id)
            if isinstance(node, ast.For):
                yield from self._check_iter(ctx, node.iter, set_names)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    yield from self._check_iter(ctx, gen.iter, set_names)
            elif isinstance(node, ast.Call):
                f = node.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if name in ITER_CALLS or name == "join":
                    for a in node.args[:1]:
                        yield from self._check_iter(ctx, a, set_names)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, set_names)

        yield from scan_scope(ctx.tree.body, set())

        # ---- global-RNG draws in library code ---------------------------
        if not ctx.rel.startswith("paddle_tpu/"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            f = node.func
            if isinstance(f.value, ast.Name) and \
                    f.value.id in rng_aliases and f.attr not in RNG_SAFE:
                if not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"`{f.value.id}.{f.attr}()` draws from the "
                        "process-global random stream — library code "
                        "must use a private `random.Random(seed)` (the "
                        "PR-3 retry-jitter bug shifted seeded "
                        "reader.shuffle streams)")
            elif isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id in nprng_bases and \
                    f.value.attr == "random" and f.attr not in RNG_SAFE:
                if not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"`np.random.{f.attr}()` draws from numpy's "
                        "global RNG — use np.random.default_rng(seed) / "
                        "a Generator owned by the caller")

    def _check_iter(self, ctx, iter_expr, set_names):
        if _is_set_expr(iter_expr, set_names):
            if not ctx.suppressed(self.id, iter_expr.lineno):
                yield self.finding(
                    ctx, iter_expr,
                    "iteration over an unordered set — order is "
                    "PYTHONHASHSEED-dependent; `sorted(...)` it before "
                    "it feeds state/program construction (the PR-2 "
                    "`set(a) | set(b)` gradient-merge corruption)")
