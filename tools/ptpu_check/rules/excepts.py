"""silent-except — no silently-swallowed failures.

Re-homed from ``tools/lint_excepts.py`` (PR 3): a resilience runtime is
only trustworthy if failures can't vanish.  Rejects (1) bare
``except:`` anywhere — it catches SystemExit/KeyboardInterrupt and
would eat the preemption handler's exit — and (2) ``except Exception:``
/ ``except BaseException:`` whose body is only ``pass``/``...``.

Suppress with ``ptpu-check[silent-except]: why`` (or the legacy
``justified:`` comment tag) anywhere in the handler's extent.
"""
from __future__ import annotations

import ast

from ..core import Rule

BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD
                   for e in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/... — the exception dies with no trace."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue   # docstring or `...`
        return False
    return True


class SilentExceptRule(Rule):
    id = "silent-except"
    doc = ("bare `except:` and `except Exception: pass` swallows must "
           "carry a justification")
    descends_from = ("PR-3 resilience audit: 14 undocumented swallows, "
                     "incl. ones that would have eaten the preemption "
                     "handler's SystemExit")

    def check(self, ctx, project):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            extent = ctx.node_extent(node)
            if ctx.suppressed(self.id, node.lineno, extent_end=extent):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare `except:` (catches SystemExit/KeyboardInterrupt)"
                    " — name the exceptions, or document with "
                    "`# ptpu-check[silent-except]: ...`")
            elif _is_broad(node) and _swallows(node):
                yield self.finding(
                    ctx, node,
                    "`except Exception: pass` silently swallows failures "
                    "— narrow the types, handle it, or document with "
                    "`# ptpu-check[silent-except]: ...`")
