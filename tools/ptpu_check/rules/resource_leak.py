"""resource-leak — acquire/release escape analysis for the resources
the multi-process era leaks: sockets, KV allocations, temp dirs,
threads.

The bug classes, each fixed by hand in a past review round:

- **socket without timeout** (PR-9): a ``socket.create_connection``
  with no ``timeout=`` hung fleet registration inside ``start_server``'s
  lock forever when the store accepted but never answered.  Flagged
  unless the call passes ``timeout=`` or the bound name/attr gets a
  ``settimeout(...)`` (anywhere in the same function, or — for
  ``self.<attr>`` storage — anywhere in the file).
- **leak on error path** (PR-2/PR-3): a locally-owned resource (socket,
  ``tempfile.mkdtemp`` dir) acquired, then a raising-capable call
  before the release — the exception skips the release and the fd/dir
  leaks.  Ownership ESCAPES (returned, yielded, stored into
  ``self``/a container, passed to another call) end the analysis: the
  receiver owns cleanup.  A release inside a ``finally``/``except``
  body is exception-guarded and clean; ``with`` acquisition is always
  clean.
- **acquire/release asymmetry** (PR-2's leaked ``_requests``): a
  function that BOTH acquires and releases a keyed resource
  (``.allocate(...)``/``.free(...)``, ``.add_request(...)``/
  ``.release_request(...)`` — the pairs ``serving/kv_cache.py`` and the
  engine define) but whose release is not exception-guarded while
  raising-capable calls run in between.  A function that only acquires
  transfers ownership (the ``add_request`` shape) and is clean.
- **thread without bounded join** (PR-9/PR-11 rollups): a non-daemon
  ``threading.Thread`` started locally and never ``join(timeout)``-ed
  wedges interpreter shutdown on the thread's failure mode instead of
  surfacing it.

Honesty note: calls ON the resource itself (``sock.connect(...)``) are
not counted as raising-capable — flagging every non-``with`` socket
setup would bury the signal; the fix for those paths is ``with`` and
the rule's message says so.

Suppress with ``# ptpu-check[resource-leak]: why``.
"""
from __future__ import annotations

import ast

from ..callgraph import dotted_name, iter_body_nodes
from ..core import Rule

# effectful keyed acquire -> its paired releases (seeded from the
# repo's own lifecycle APIs: BlockKVCache.allocate/free,
# LLMEngine.add_request/release_request)
KEYED_PAIRS = {
    "allocate": ("free", "release_request"),
    "add_request": ("release_request",),
}
RELEASE_METHODS = {"close", "cleanup", "shutdown", "terminate",
                   "release", "unlink", "stop"}
RELEASE_FUNCS = {"rmtree"}   # shutil.rmtree(tmpdir)


def _socket_root(dn, idx):
    """True when dotted name `dn`'s root is the socket module."""
    if not dn:
        return False
    root = dn.split(".", 1)[0]
    if idx is not None:
        mod = idx.mod_alias.get(root, root)
        if mod == "socket":
            return True
        if dn in idx.sym_import and idx.sym_import[dn][0] == "socket":
            return True
    return root == "socket"


def _acquire_kind(call, idx):
    """('socket'|'socket_dial'|'tmpdir', needs_timeout) or None."""
    dn = dotted_name(call.func)
    if dn is None:
        return None
    last = dn.rsplit(".", 1)[-1]
    if last == "create_connection" and _socket_root(dn, idx):
        has_timeout = len(call.args) >= 2 or any(
            k.arg == "timeout" for k in call.keywords)
        return ("socket_dial", not has_timeout)
    if last == "socket" and _socket_root(dn, idx):
        return ("socket", False)
    if last == "mkdtemp" and (dn.startswith("tempfile.")
                              or (idx is not None
                                  and idx.sym_import.get(dn, ("",))[0]
                                  == "tempfile")):
        return ("tmpdir", False)
    return None


def _guarded_ranges(func_node):
    """Line ranges of finally/except bodies — releases there are
    exception-guarded."""
    ranges = []
    for n in iter_body_nodes(func_node):
        if isinstance(n, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            stmts = list(n.finalbody)
            for h in n.handlers:
                stmts.extend(h.body)
            for stmt in stmts:
                ranges.append((stmt.lineno,
                               getattr(stmt, "end_lineno", stmt.lineno)))
    return ranges


def _in_ranges(line, ranges):
    return any(lo <= line <= hi for lo, hi in ranges)


class _Resource:
    __slots__ = ("kind", "name", "node", "released_at", "guarded",
                 "escaped", "has_settimeout", "needs_timeout",
                 "started", "joined_bounded", "joined_unbounded",
                 "daemon", "connects")

    def __init__(self, kind, name, node, needs_timeout=False,
                 daemon=False):
        self.kind = kind
        self.name = name
        self.node = node
        self.needs_timeout = needs_timeout
        self.released_at = None
        self.guarded = False
        self.escaped = False
        self.has_settimeout = False
        self.started = False
        self.joined_bounded = False
        self.joined_unbounded = False
        self.daemon = daemon
        self.connects = False


class ResourceLeakRule(Rule):
    id = "resource-leak"
    doc = ("sockets dialed without timeouts, locally-owned resources "
           "leaked on exception paths, acquire/release asymmetry, "
           "threads without bounded join")
    descends_from = ("PR-9: a store that accepted but never answered "
                     "hung registration forever (no socket timeout); "
                     "PR-2: `_requests` grew unboundedly until "
                     "generate() released in a finally")

    TRIGGERS = ("socket", "mkdtemp", "Thread", ".allocate(",
                ".add_request(")

    def check(self, ctx, project):
        # cheap pre-filter: a file mentioning none of the acquire
        # surfaces has nothing for the per-function scans to find
        if not any(t in ctx.src for t in self.TRIGGERS):
            return
        cg = project.callgraph
        idx = cg.index_of(ctx.rel)
        # file-wide: attributes that receive .settimeout anywhere
        # (self._sock stored in __init__, settimeout'd in _connect)
        attr_settimeout = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "settimeout" \
                    and isinstance(n.func.value, ast.Attribute):
                attr_settimeout.add(n.func.value.attr)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, idx, node,
                                                attr_settimeout)

    # -- per-function analysis --------------------------------------------

    def _check_function(self, ctx, idx, func, attr_settimeout):
        guarded = _guarded_ranges(func)
        resources = {}    # local name -> _Resource
        attr_dials = []   # (attr, node) create_connection w/o timeout
        keyed = {}        # acquire attr -> list of call nodes
        keyed_rel = {}    # release attr -> list of (node, guarded?)
        calls_after = []  # (line, call node) raising-capable calls

        # iter_body_nodes is stack-order; the scan below is
        # order-sensitive (a resource must be registered before its
        # method calls are classified), so sort into source order
        nodes = sorted(iter_body_nodes(func),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        for n in nodes:
            if isinstance(n, ast.withitem):
                # `with <acquire>(...) as x` — the RELEASE is guaranteed
                # by the context manager, but the TIMEOUT discipline is
                # not: `with socket.create_connection((h, p)):` still
                # hangs forever on a peer that accepts and never answers
                # (rewriting the PR-9 bug with `with` must not hide it).
                # Register the resource escaped (leak checks off) so the
                # needs_timeout check — and an in-body settimeout — are
                # still seen.
                if isinstance(n.context_expr, ast.Call):
                    kind = _acquire_kind(n.context_expr, idx)
                    if kind is not None:
                        name = n.optional_vars.id if isinstance(
                            n.optional_vars, ast.Name) else None
                        r = _Resource(kind[0], name, n.context_expr,
                                      needs_timeout=kind[1])
                        r.escaped = True
                        resources[name or f"<with:{n.context_expr.lineno}>"] = r
                    continue
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.value, ast.Call):
                kind = _acquire_kind(n.value, idx)
                tgt = n.targets[0]
                if kind is not None:
                    if isinstance(tgt, ast.Name):
                        resources[tgt.id] = _Resource(
                            kind[0], tgt.id, n.value,
                            needs_timeout=kind[1])
                        continue
                    if isinstance(tgt, ast.Attribute) and kind[1]:
                        # stored into self.<attr>: ownership escapes but
                        # the timeout discipline is still checkable
                        if tgt.attr not in attr_settimeout:
                            attr_dials.append((tgt.attr, n.value))
                        continue
                thr = self._thread_ctor(n.value, idx)
                if thr is not None and isinstance(tgt, ast.Name):
                    resources[tgt.id] = _Resource(
                        "thread", tgt.id, n.value, daemon=thr)
                    continue
            if isinstance(n, ast.Call):
                dn = dotted_name(n.func)
                if isinstance(n.func, ast.Attribute):
                    base, attr = n.func.value, n.func.attr
                    if isinstance(base, ast.Name) \
                            and base.id in resources:
                        r = resources[base.id]
                        self._on_method(r, attr, n, guarded)
                        continue   # calls ON the resource: not risky
                    if attr in KEYED_PAIRS:
                        keyed.setdefault(attr, []).append(n)
                    for acq, rels in KEYED_PAIRS.items():
                        if attr in rels:
                            keyed_rel.setdefault(attr, []).append(
                                (n, _in_ranges(n.lineno, guarded)))
                    if dn and dn.rsplit(".", 1)[-1] in RELEASE_FUNCS:
                        for a in n.args:
                            if isinstance(a, ast.Name) \
                                    and a.id in resources:
                                r = resources[a.id]
                                r.released_at = n.lineno
                                r.guarded |= _in_ranges(n.lineno,
                                                        guarded)
                # a raising-capable call (unless it IS an acquire)
                if _acquire_kind(n, idx) is None:
                    calls_after.append((n.lineno, n))
                # escapes: the resource passed onward as an argument
                for a in list(n.args) + [k.value for k in n.keywords]:
                    if isinstance(a, ast.Name) and a.id in resources \
                            and not (dn and dn.rsplit(".", 1)[-1]
                                     in RELEASE_FUNCS):
                        resources[a.id].escaped = True
            elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
                v = getattr(n, "value", None)
                if v is not None:
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.Name) \
                                and sub.id in resources:
                            resources[sub.id].escaped = True
            elif isinstance(n, ast.Assign):
                # aliased or stored elsewhere -> ownership escapes
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Name) \
                            and sub.id in resources:
                        resources[sub.id].escaped = True
                for t in n.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name) \
                                    and sub.id in resources:
                                resources[sub.id].escaped = True

        yield from self._emit(ctx, func, resources, attr_dials, keyed,
                              keyed_rel, calls_after)

    def _on_method(self, r, attr, call, guarded):
        if attr == "settimeout":
            r.has_settimeout = True
        elif attr == "connect":
            r.connects = True
        elif attr == "start":
            r.started = True
        elif attr == "join":
            if call.args or call.keywords:
                r.joined_bounded = True
            else:
                r.joined_unbounded = True
        elif attr in RELEASE_METHODS:
            r.released_at = call.lineno
            r.guarded |= _in_ranges(call.lineno, guarded)

    def _thread_ctor(self, call, idx):
        """threading.Thread(...) -> daemon flag (True/False), else
        None."""
        dn = dotted_name(call.func)
        if dn is None or dn.rsplit(".", 1)[-1] != "Thread":
            return None
        for k in call.keywords:
            if k.arg == "daemon":
                return bool(isinstance(k.value, ast.Constant)
                            and k.value.value)
        return False

    def _emit(self, ctx, func, resources, attr_dials, keyed, keyed_rel,
              calls_after):
        for attr, node in attr_dials:
            if not ctx.suppressed(self.id, node.lineno):
                yield self.finding(
                    ctx, node,
                    f"socket dialed without a timeout into "
                    f"`self.{attr}` — a peer that accepts but never "
                    f"answers blocks forever (the PR-9 hung-"
                    f"registration class); pass timeout= or "
                    f"settimeout() before IO")
        for r in resources.values():
            line = r.node.lineno
            risky_after = [c for ln, c in calls_after
                           if ln > line
                           and (r.released_at is None
                                or ln <= r.released_at)]
            if r.kind == "socket_dial" and r.needs_timeout \
                    and not r.has_settimeout:
                if not ctx.suppressed(self.id, line):
                    yield self.finding(
                        ctx, r.node,
                        f"socket dialed without a timeout "
                        f"(`{r.name}`) — a peer that accepts but "
                        f"never answers blocks forever (the PR-9 "
                        f"hung-registration class); pass timeout= or "
                        f"settimeout() before IO")
            if r.kind == "socket" and r.connects \
                    and not r.has_settimeout:
                if not ctx.suppressed(self.id, line):
                    yield self.finding(
                        ctx, r.node,
                        f"`{r.name}.connect(...)` on a socket with no "
                        f"settimeout() — the dial blocks unboundedly "
                        f"on an unresponsive peer (PR-9 class)")
            if r.kind == "thread":
                if r.started and not r.daemon and not r.escaped \
                        and not r.joined_bounded:
                    if not ctx.suppressed(self.id, line):
                        how = ("join() has no timeout"
                               if r.joined_unbounded
                               else "never joined")
                        yield self.finding(
                            ctx, r.node,
                            f"non-daemon thread `{r.name}` started "
                            f"but {how} — a wedged worker blocks "
                            f"interpreter shutdown forever; "
                            f"join(timeout) and handle the survivor, "
                            f"or make it a daemon")
                continue
            if r.kind in ("socket", "socket_dial", "tmpdir") \
                    and not r.escaped:
                if r.released_at is None and risky_after:
                    if not ctx.suppressed(self.id, line):
                        noun = ("temp dir" if r.kind == "tmpdir"
                                else "socket")
                        yield self.finding(
                            ctx, r.node,
                            f"locally-owned {noun} `{r.name}` is "
                            f"never released on this path — an "
                            f"exception in the calls that follow "
                            f"leaks it; use `with`, or release in a "
                            f"finally")
                elif r.released_at is not None and not r.guarded \
                        and risky_after:
                    if not ctx.suppressed(self.id, line):
                        noun = ("temp dir" if r.kind == "tmpdir"
                                else "socket")
                        yield self.finding(
                            ctx, r.node,
                            f"{noun} `{r.name}` is released on line "
                            f"{r.released_at} but a raising-capable "
                            f"call runs before it — the exception "
                            f"path leaks the {noun}; move the release "
                            f"into a finally (the PR-2 "
                            f"release-in-finally shape) or use `with`")
        # keyed acquire/release asymmetry: the function manages the
        # lifecycle locally but not exception-safely
        for acq, nodes in keyed.items():
            rel_names = KEYED_PAIRS[acq]
            rels = [p for rn in rel_names
                    for p in keyed_rel.get(rn, [])]
            if not rels:
                continue   # acquire-only: ownership transferred
            if any(g for _, g in rels):
                continue   # at least one exception-guarded release
            first_rel = min(n.lineno for n, _ in rels)
            for node in nodes:
                risky = [c for ln, c in calls_after
                         if node.lineno < ln <= first_rel
                         and c is not node
                         and all(c is not rn for rn, _ in rels)]
                if risky and not ctx.suppressed(self.id, node.lineno):
                    yield self.finding(
                        ctx, node,
                        f"`.{acq}(...)` is paired with "
                        f"`.{'/'.join(rel_names)}` in this function "
                        f"but the release is not exception-guarded — "
                        f"a raise in between leaks the acquisition "
                        f"(the PR-2 leaked-`_requests` class); "
                        f"release in a finally")
