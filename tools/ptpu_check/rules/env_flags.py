"""env-flag-drift — every ``PTPU_*`` flag read in code must be in the
README, and every README flag must still exist in code.  Both
directions.

The bug class: the flag surface grew one env var per PR
(``PTPU_MONITOR``, ``PTPU_TRACE``, ``PTPU_FAULTS``, ``PTPU_RAGGED``,
...) and the README's documented set drifted behind the code's read
set — an operator tuning a fleet cannot discover half the knobs, and a
documented knob that silently stopped being read is worse (set it,
believe it, get nothing).  The multi-process era (fleet env plumbing,
per-rank ``PTPU_REPLICA_ID``) multiplies the surface.

Mechanics: flag READS/WRITES are collected from ``os.environ.get /
os.getenv / environ[...] / environ.setdefault / environ.pop`` call
sites whose key is a full ``PTPU_*`` string literal.  The documented
set is every ``PTPU_*`` token in the repo-root ``README.md``.  For the
README→code direction, root-level driver scripts outside the analyzer's
default scope (``bench.py`` etc.) and ``examples/`` are included via a
light text scan, so a flag read only there does not get flagged as
phantom.

- code→README: an undocumented flag is flagged AT ITS READ SITE (fix:
  document it in the README "Environment flags" table, or suppress with
  ``# ptpu-check[env-flag-drift]: why`` for genuinely-internal debug
  knobs);
- README→code: a documented flag with no read anywhere is flagged with
  ``path=README.md`` at its first mention line (fix: delete the doc row
  or restore the reader — there is no inline suppression in markdown;
  a deliberately-documented-ahead flag belongs in the baseline).

No README.md at the repo root → the rule is silent (fixture runs).
"""
from __future__ import annotations

import ast
import os
import re

from ..callgraph import dotted_name
from ..core import Finding, Rule

FLAG_RE = re.compile(r"PTPU_[A-Z0-9]+(?:_[A-Z0-9]+)*")
ENV_CALL_LASTS = {"get", "getenv", "setdefault", "pop"}
# root-level .py files + examples/ are outside the analyzer's default
# scope but still read flags (bench.py's PTPU_BENCH_HISTORY), and shell
# CI lanes read flags too (run_ci.sh's PTPU_CHECK_BASE); scan them
# textually for the README→code direction only
EXTRA_SCAN_DIRS = ("", "examples", "tools", "scripts")
EXTRA_SCAN_EXTS = (".py", ".sh")


def _env_flag_sites(ctx):
    """[(flag, node)] for every PTPU_* literal used as an environ key."""
    out = []
    for node in ast.walk(ctx.tree):
        key = None
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func) or ""
            last = dn.rsplit(".", 1)[-1]
            is_env = ("environ" in dn and last in ENV_CALL_LASTS) \
                or last == "getenv"
            if is_env and node.args:
                key = node.args[0]
        elif isinstance(node, ast.Subscript):
            dn = dotted_name(node.value) or ""
            if dn.endswith("environ"):
                key = node.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            m = FLAG_RE.fullmatch(key.value)
            if m:
                out.append((key.value, node))
    return out


def _readme(project):
    """(lines list, {flag: first line no}) from the repo-root README, or
    (None, {}) when absent.  Cached on the project."""
    cached = getattr(project, "_env_readme", None)
    if cached is not None:
        return cached
    lines, flags = None, {}
    root = getattr(project, "repo_root", None)
    path = os.path.join(root, "README.md") if root else None
    if path and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        for i, ln in enumerate(lines, start=1):
            for m in FLAG_RE.finditer(ln):
                flags.setdefault(m.group(0), i)
    project._env_readme = (lines, flags)
    return project._env_readme


def _code_flags(project):
    """All flags used anywhere in code: analyzed contexts' env sites
    plus the light out-of-scope text scan.  Cached on the project."""
    cached = getattr(project, "_env_code_flags", None)
    if cached is not None:
        return cached
    used = set()
    for ctx in project.contexts:
        if ctx.tree is None:
            continue
        for flag, _ in _env_flag_sites(ctx):
            used.add(flag)
    root = getattr(project, "repo_root", None)
    if root:
        for sub in EXTRA_SCAN_DIRS:
            d = os.path.join(root, sub)
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if not name.endswith(EXTRA_SCAN_EXTS):
                    continue
                try:
                    with open(os.path.join(d, name),
                              encoding="utf-8") as f:
                        used.update(FLAG_RE.findall(f.read()))
                except OSError:
                    continue
    project._env_code_flags = used
    return used


class EnvFlagDriftRule(Rule):
    id = "env-flag-drift"
    doc = ("every PTPU_* env flag read in code is documented in README "
           "and every documented flag is still read — both directions")
    descends_from = ("PRs 1-13 each added env knobs (PTPU_MONITOR, "
                     "PTPU_TRACE, PTPU_FAULTS, ...); 20+ reads had "
                     "drifted out of the README's documented set by "
                     "PR 11 — undiscoverable fleet tuning knobs")

    def check(self, ctx, project):
        readme_lines, readme_flags = _readme(project)
        if readme_lines is None:
            return
        # code -> README: flag each undocumented read site (first site
        # per flag per file keeps the noise proportional to flags, not
        # call sites)
        seen_here = set()
        for flag, node in _env_flag_sites(ctx):
            if flag in readme_flags or flag in seen_here:
                continue
            seen_here.add(flag)
            if not ctx.suppressed(self.id, node.lineno):
                yield self.finding(
                    ctx, node,
                    f"`{flag}` is read here but documented nowhere in "
                    f"README.md — add it to the \"Environment flags\" "
                    f"table (operators cannot discover undocumented "
                    f"knobs)")
        # README -> code: emitted once, from the lexicographically first
        # analyzed context so the report stays deterministic and
        # single-copy.  Only meaningful when the analyzed set actually
        # covers the tree — a partial-path run (`ptpu_check one.py`)
        # cannot see the readers and every documented flag would look
        # phantom; gate on the package root being in scope.
        if "paddle_tpu/__init__.py" not in project.by_rel:
            return
        if project.contexts and ctx is project.contexts[0]:
            used = _code_flags(project)
            for flag, line in sorted(readme_flags.items()):
                if flag not in used:
                    yield Finding(
                        self.id, "README.md", line, 0,
                        f"`{flag}` is documented but read nowhere in "
                        f"code — a knob operators can set with no "
                        f"effect; delete the row or restore the reader")
