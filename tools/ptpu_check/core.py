"""Framework core: file contexts (one parse per file), findings,
suppression markers, the rule registry, and the baseline workflow.

Suppression marker (unified scheme)::

    # ptpu-check[<rule-id>]: <justification — required, non-empty>
    # ptpu-check[<rule-a>,<rule-b>]: <one justification for both>

placed on the flagged line or the line directly above it (for
``silent-except`` the whole handler extent counts, matching the old
``lint_excepts`` contract).  Legacy markers stay honored so old
branches/backports don't break: the legacy ``justified:`` comment tag
suppresses ``silent-except`` and ``metric-ok:`` suppresses
``metric-hygiene`` with their original placement rules.

Baseline: ``tools/ptpu_check/baseline.json`` holds audited pre-existing
findings keyed by (rule, path, stripped source line text) with a count —
stable across unrelated line moves.  ``--write-baseline`` regenerates
it; a baselined site that gets FIXED simply stops matching (stale
entries are harmless and pruned on the next ``--write-baseline``).
"""
from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

MARKER_RE = re.compile(r"#\s*ptpu-check\[([a-z0-9_,\- ]+)\]:\s*(\S.*)?")
LEGACY_JUSTIFIED = "justified:"
LEGACY_METRIC_OK = "metric-ok:"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def fingerprint(self, ctx: "FileContext") -> tuple:
        """(rule, path, stripped-line-text): survives line renumbering."""
        text = ""
        if 1 <= self.line <= len(ctx.lines):
            text = ctx.lines[self.line - 1].strip()
        return (self.rule, self.path, text)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


class FileContext:
    """One file, parsed once and shared by every rule."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = None
        self.syntax_error = None
        try:
            self.tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            self.syntax_error = e
        self._markers = None   # line(1-based) -> set of rule ids

    # -- suppression -------------------------------------------------------

    @property
    def markers(self) -> dict:
        if self._markers is None:
            self._markers = {}
            for i, ln in enumerate(self.lines, start=1):
                m = MARKER_RE.search(ln)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    if m.group(2):   # justification present
                        self._markers[i] = rules
                if LEGACY_JUSTIFIED in ln:
                    self._markers.setdefault(i, set()).add("silent-except")
                if LEGACY_METRIC_OK in ln:
                    self._markers.setdefault(i, set()).add("metric-hygiene")
        return self._markers

    def bare_markers(self):
        """Lines carrying a ptpu-check[...] marker WITHOUT justification
        text — surfaced as findings so suppressions can't be silent."""
        out = []
        for i, ln in enumerate(self.lines, start=1):
            m = MARKER_RE.search(ln)
            if m and not m.group(2):
                out.append(i)
        return out

    def suppressed(self, rule: str, line: int, extent_end: int = None) -> bool:
        """Marker for `rule` on the flagged line, in the contiguous
        comment block directly above it (multi-line justifications are
        encouraged), on the single code line above (trailing marker), or
        — when extent_end is given, e.g. an except handler — anywhere in
        [line, extent_end]."""
        last = extent_end if extent_end is not None else line
        for i in range(line, last + 1):
            if rule in self.markers.get(i, ()):
                return True
        i = line - 1
        while i >= 1:
            if rule in self.markers.get(i, ()):
                return True
            if not self.lines[i - 1].lstrip().startswith("#"):
                break   # non-comment line above: checked, ends the walk
            i -= 1
        return False

    def node_extent(self, node) -> int:
        last = getattr(node, "lineno", 1)
        for n in ast.walk(node):
            end = getattr(n, "end_lineno", None)
            if end is not None:
                last = max(last, end)
        return last


class Rule:
    """Subclass and register.  `check(ctx, project)` yields Findings for
    one file; cross-file state comes from `project` (e.g. the call
    graph), which is shared and built lazily."""

    id: str = ""
    doc: str = ""          # one-liner for --list-rules / README parity
    descends_from: str = ""  # the historical bug this rule mechanizes

    def check(self, ctx: FileContext, project: "Project"):
        raise NotImplementedError

    def finding(self, ctx: FileContext, node, message: str) -> Finding:
        return Finding(self.id, ctx.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


class Project:
    """All files under analysis + lazily-built cross-file artifacts.
    `repo_root` lets document-facing rules (env-flag-drift) read
    non-Python sources like README.md without putting them through the
    Python parse/marker machinery."""

    def __init__(self, contexts, repo_root=None):
        self.contexts = list(contexts)
        self.by_rel = {c.rel: c for c in self.contexts}
        self.repo_root = repo_root
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from . import callgraph
            self._callgraph = callgraph.CallGraph(self.contexts)
        return self._callgraph


# -- collection -------------------------------------------------------------

SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def collect_files(paths, repo_root):
    """Yield (abspath, relpath) for every .py under `paths` (files or
    dirs), sorted for deterministic output."""
    seen = set()
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    fp = os.path.join(dirpath, name)
                    if fp not in seen:
                        seen.add(fp)
                        out.append(fp)
    out.sort()
    for fp in out:
        rel = os.path.relpath(fp, repo_root)
        yield fp, rel


def load_context(path, rel):
    with tokenize.open(path) as f:   # honors coding cookies
        src = f.read()
    return FileContext(path, rel, src)


# -- baseline ---------------------------------------------------------------

@dataclass
class Baseline:
    entries: dict = field(default_factory=dict)  # fingerprint -> count

    @classmethod
    def load(cls, path):
        if not path or not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        entries = {}
        for e in doc.get("entries", []):
            key = (e["rule"], e["path"], e["code"])
            entries[key] = entries.get(key, 0) + int(e.get("count", 1))
        return cls(entries)

    @staticmethod
    def _key(f, contexts_by_rel):
        """Fingerprint via the source line when the finding lives in an
        analyzed .py file; document findings (README.md) fall back to
        (rule, path, message) — the message embeds the flag name, so the
        key is as move-stable as a line fingerprint."""
        ctx = contexts_by_rel.get(f.path)
        if ctx is not None:
            return f.fingerprint(ctx)
        return (f.rule, f.path, f.message)

    @classmethod
    def from_findings(cls, findings, contexts_by_rel):
        entries = {}
        for f in findings:
            key = cls._key(f, contexts_by_rel)
            entries[key] = entries.get(key, 0) + 1
        return cls(entries)

    def save(self, path):
        rows = [{"rule": r, "path": p, "code": c, "count": n}
                for (r, p, c), n in sorted(self.entries.items())]
        doc = {"version": 1,
               "comment": ("Audited pre-existing findings; regenerate with "
                           "`python -m tools.ptpu_check --write-baseline`. "
                           "New code must be clean or carry an inline "
                           "`# ptpu-check[<rule>]: why` marker."),
               "entries": rows}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=False)
            f.write("\n")

    def partition(self, findings, contexts_by_rel):
        """Split findings into (new, baselined).  Each baseline entry
        absorbs at most `count` findings with its fingerprint."""
        budget = dict(self.entries)
        new, old = [], []
        for f in findings:
            key = self._key(f, contexts_by_rel)
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old
