"""Cross-file call graph + jit/pjit/trace-entry reachability.

Scope and honesty: this is a LINT-grade graph, not a type checker.  It
resolves (a) plain-name calls/references through the lexical chain
(nested defs -> module top level -> imports), (b) ``self.method`` inside
a class — including methods BOUND via ``self.<attr> = <callable>``
assignments (the engine's ``self._fn = _impl`` pattern dropped edges in
v1, silently shrinking host-sync reachability), (c) ``Class.method``
references by class name, and (d) ``alias.func`` where ``alias`` is an
imported module that is part of the analyzed file set — ``import x.y as
z`` and ``from x import y as z`` forms included (``functools.partial``
under an alias is resolved too).  Dynamic dispatch, inheritance and
higher-order returns are over/under-approximated; rules built on it
(host-sync, blocking-in-handler, recompile-hazard) pair with the
baseline/suppression workflow for the residue.

Trace entries — where XLA tracing starts and host syncs become hidden
recompiles/transfers:

- calls of the jit family (``jax.jit``/``pjit``/``vmap``/``pmap``/
  ``grad``/``value_and_grad``/``checkpoint``/``remat``/``eval_shape``,
  ``jax.lax.scan/while_loop/cond/fori_loop/switch/map``,
  ``shard_map``/``shard_map_compat``): every argument that resolves to
  a known function becomes an entry;
- functions decorated with any of the above, incl. through
  ``functools.partial(jax.jit, ...)``.

A function REFERENCED (not just called) inside a traced function is
itself treated as traced — that is exactly the engine's
``builder``/``attn_fn`` closure-callback pattern.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

JIT_DOTTED_LAST = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad",
    "checkpoint", "remat", "eval_shape", "make_jaxpr",
    "scan", "while_loop", "cond", "fori_loop", "switch", "map",
    "shard_map",
}
# bare names that are unambiguous even without a jax-rooted dotted path
JIT_BARE = {"pjit", "shard_map", "shard_map_compat"}


def dotted_name(node):
    """'jax.lax.scan' for Attribute chains rooted at a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_body_nodes(func_node):
    """Walk a function body WITHOUT descending into nested function/class
    definitions (each nested def is its own call-graph node)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


@dataclass
class FuncInfo:
    key: tuple              # (rel, qualname)
    node: object            # ast.FunctionDef
    rel: str
    qualname: str
    class_name: str = None  # immediate enclosing class, if a method
    parent: "FuncInfo" = None   # lexically enclosing function
    locals_: dict = field(default_factory=dict)   # name -> FuncInfo (nested)


class ModuleIndex:
    """Per-module symbol + import tables."""

    def __init__(self, ctx, dotted):
        self.rel = ctx.rel
        self.dotted = dotted           # e.g. 'paddle_tpu.serving.engine'
        self.top = {}                  # name -> FuncInfo (module level)
        self.classes = {}              # class name -> {meth name -> FuncInfo}
        self.class_attrs = {}          # class name -> {attr -> FuncInfo}
        #                                (self.<attr> = <callable> bindings)
        self.mod_alias = {}            # local name -> dotted module
        self.sym_import = {}           # local name -> (dotted module, symbol)

    def package(self):
        """Dotted package for resolving relative imports."""
        if self.rel.endswith("__init__.py"):
            return self.dotted
        return self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""


def _module_dotted(rel):
    parts = rel[:-3].split("/")        # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    def __init__(self, contexts):
        self.functions = {}            # key -> FuncInfo
        self._by_node = {}             # id(ast node) -> FuncInfo
        self.indexes = {}              # rel -> ModuleIndex
        self._dotted_to_rel = {}
        self.entries = {}              # key -> reason str
        self.traced = {}               # key -> origin entry description

        ctxs = [c for c in contexts if c.tree is not None]
        for c in ctxs:
            self._dotted_to_rel[_module_dotted(c.rel)] = c.rel
        for c in ctxs:
            self._index_module(c)
        for c in ctxs:
            self._resolve_imports(c)
        for c in ctxs:
            self._index_class_attrs(c)
        self._edges = {}               # key -> set of keys
        for c in ctxs:
            self._collect_edges_and_entries(c)
        self._propagate()
        self._redges = None            # reverse edges, built lazily

    # -- indexing ----------------------------------------------------------

    def _index_module(self, ctx):
        idx = ModuleIndex(ctx, _module_dotted(ctx.rel))
        self.indexes[ctx.rel] = idx

        def visit(node, qual, class_name, parent):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    fi = FuncInfo((ctx.rel, q), child, ctx.rel, q,
                                  class_name=class_name, parent=parent)
                    self.functions[fi.key] = fi
                    self._by_node[id(child)] = fi
                    if parent is not None:
                        parent.locals_[child.name] = fi
                    elif class_name is not None:
                        idx.classes.setdefault(class_name,
                                               {})[child.name] = fi
                    else:
                        idx.top[child.name] = fi
                    visit(child, q, None, fi)
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    visit(child, q, child.name, None)
                else:
                    visit(child, qual, class_name, parent)

        visit(ctx.tree, "", None, None)

    def _resolve_imports(self, ctx):
        idx = self.indexes[ctx.rel]
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    # `import x.y` binds `x`; `import x.y as z` binds z->x.y
                    local = a.asname or a.name.split(".")[0]
                    idx.mod_alias[local] = (a.name if a.asname
                                            else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg_parts = idx.package().split(".") if idx.package() \
                        else []
                    cut = len(pkg_parts) - (node.level - 1)
                    base_parts = pkg_parts[:max(cut, 0)]
                    if node.module:
                        base_parts.append(node.module)
                    base = ".".join(base_parts)
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    sub = f"{base}.{a.name}" if base else a.name
                    if sub in self._dotted_to_rel:
                        idx.mod_alias[local] = sub       # submodule import
                    else:
                        idx.sym_import[local] = (base, a.name)

    def _index_class_attrs(self, ctx):
        """``self.<attr> = <callable>`` bindings inside a class's methods
        bind the attribute to that callable for every ``self.<attr>(...)``
        call site in the class (v1 dropped these edges).  Runs AFTER
        import resolution so the assigned value can be a module function,
        an imported symbol, or a sibling method."""
        idx = self.indexes[ctx.rel]
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = idx.class_attrs.setdefault(node.name, {})
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                fi = self._by_node.get(id(meth))
                for n in iter_body_nodes(meth):
                    if not isinstance(n, ast.Assign):
                        continue
                    for t in n.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            tgt = self.resolve(n.value, idx, fi)
                            if tgt is not None:
                                attrs.setdefault(t.attr, tgt)

    # -- resolution --------------------------------------------------------

    def resolve(self, expr, idx, func=None):
        """Resolve a reference expression to a FuncInfo, or None."""
        if isinstance(expr, ast.Name):
            f = func
            while f is not None:
                if expr.id in f.locals_:
                    return f.locals_[expr.id]
                f = f.parent
            if expr.id in idx.top:
                return idx.top[expr.id]
            if expr.id in idx.sym_import:
                mod, sym = idx.sym_import[expr.id]
                rel = self._dotted_to_rel.get(mod)
                if rel is not None:
                    return self.indexes[rel].top.get(sym)
            return None
        if isinstance(expr, ast.Attribute):
            v = expr.value
            if isinstance(v, ast.Name):
                if v.id == "self" and func is not None:
                    cls = func.class_name
                    f = func
                    while cls is None and f.parent is not None:
                        f = f.parent
                        cls = f.class_name
                    if cls is not None:
                        hit = idx.classes.get(cls, {}).get(expr.attr)
                        if hit is None:   # self.<attr> = <callable>
                            hit = idx.class_attrs.get(cls,
                                                      {}).get(expr.attr)
                        return hit
                if v.id in idx.classes:   # Class.method reference
                    return idx.classes[v.id].get(expr.attr)
                mod = self._local_module(v.id, idx)
                if mod is not None:
                    rel = self._dotted_to_rel.get(mod)
                    if rel is not None:
                        return self.indexes[rel].top.get(expr.attr)
        return None

    def _local_module(self, name, idx):
        return idx.mod_alias.get(name)

    def is_jit_entry_callable(self, func_expr, idx):
        """Does this call expression start a trace?"""
        dn = dotted_name(func_expr)
        if dn:
            last = dn.rsplit(".", 1)[-1]
            root = dn.split(".", 1)[0]
            root_mod = idx.mod_alias.get(root, root)
            if last in JIT_DOTTED_LAST and (
                    root_mod == "jax" or root_mod.startswith("jax.")):
                return True
            if last in JIT_BARE:
                return True
            if dn in idx.sym_import:
                mod, sym = idx.sym_import[dn]
                if sym in JIT_DOTTED_LAST and mod.startswith("jax"):
                    return True
                if sym in JIT_BARE:
                    return True
        return False

    def _is_partial_of_jit(self, call, idx):
        """functools.partial(jax.jit, ...) (decorator form) — incl.
        ``from functools import partial as P`` aliases (a v1 gap: the
        aliased form dropped the entry, shrinking host-sync scope)."""
        dn = dotted_name(call.func)
        if dn is None:
            return False
        if dn.rsplit(".", 1)[-1] != "partial" and dn != "partial":
            # aliased symbol import: resolve the local name back to
            # ('functools', 'partial')
            if "." in dn or idx.sym_import.get(dn) != ("functools",
                                                       "partial"):
                return False
        return bool(call.args) and self.is_jit_entry_callable(call.args[0],
                                                              idx)

    # -- edges + entries ---------------------------------------------------

    def _collect_edges_and_entries(self, ctx):
        idx = self.indexes[ctx.rel]
        file_funcs = [fi for fi in self.functions.values()
                      if fi.rel == ctx.rel]
        for fi in file_funcs:
            # decorator-declared entries
            for dec in fi.node.decorator_list:
                if (self.is_jit_entry_callable(dec, idx)
                        or (isinstance(dec, ast.Call)
                            and (self.is_jit_entry_callable(dec.func, idx)
                                 or self._is_partial_of_jit(dec, idx)))):
                    self.entries.setdefault(
                        fi.key, f"decorated at {ctx.rel}:{dec.lineno}")
            edges = self._edges.setdefault(fi.key, set())
            for n in iter_body_nodes(fi.node):
                if isinstance(n, ast.Call) and \
                        self.is_jit_entry_callable(n.func, idx):
                    for arg in list(n.args) + [k.value for k in n.keywords]:
                        tgt = self.resolve(arg, idx, fi)
                        if tgt is not None:
                            self.entries.setdefault(
                                tgt.key,
                                f"passed to {dotted_name(n.func)} at "
                                f"{ctx.rel}:{n.lineno}")
                elif isinstance(n, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(n, "ctx", None), ast.Load):
                    tgt = self.resolve(n, idx, fi)
                    if tgt is not None and tgt.key != fi.key:
                        edges.add(tgt.key)
        # module-level jit calls (g = jax.jit(f) at top level)
        for n in iter_body_nodes(ctx.tree):
            if isinstance(n, ast.Call) and \
                    self.is_jit_entry_callable(n.func, idx):
                for arg in list(n.args) + [k.value for k in n.keywords]:
                    tgt = self.resolve(arg, idx, None)
                    if tgt is not None:
                        self.entries.setdefault(
                            tgt.key,
                            f"passed to {dotted_name(n.func)} at "
                            f"{ctx.rel}:{n.lineno}")

    def _propagate(self):
        work = list(self.entries)
        for k in work:
            self.traced[k] = self.entries[k]
        while work:
            k = work.pop()
            origin = self.traced[k]
            for tgt in self._edges.get(k, ()):
                if tgt not in self.traced:
                    self.traced[tgt] = origin
                    work.append(tgt)

    # -- rule-facing API ---------------------------------------------------

    def traced_functions_in(self, rel):
        out = []
        for key, origin in self.traced.items():
            if key[0] == rel:
                out.append((self.functions[key], origin))
        out.sort(key=lambda p: p[0].node.lineno)
        return out

    def index_of(self, rel):
        return self.indexes.get(rel)

    def reachable_from(self, seeds):
        """{key: origin description} for every function reachable from
        the seed set ({key: origin}) through call/reference edges —
        the generic BFS the handler-context and --changed analyses ride
        (the jit-entry propagation is the same walk with its own seeds)."""
        out = dict(seeds)
        work = list(seeds)
        while work:
            k = work.pop()
            origin = out[k]
            for tgt in self._edges.get(k, ()):
                if tgt not in out:
                    out[tgt] = origin
                    work.append(tgt)
        return out

    def _reverse_edges(self):
        if self._redges is None:
            self._redges = {}
            for src, tgts in self._edges.items():
                for t in tgts:
                    self._redges.setdefault(t, set()).add(src)
        return self._redges

    def file_closure(self, rels):
        """Transitive file-level closure of `rels` in BOTH directions:
        files whose functions call into `rels` (their findings may change
        when a callee changes — e.g. a helper gaining a host sync) AND
        files `rels`' functions reach (a changed caller can put a new
        jit entry above an unchanged callee).  The --changed target set."""
        want = set(rels)
        seeds = [k for k in self.functions if k[0] in want]
        for graph in (self._edges, self._reverse_edges()):
            work = list(seeds)
            seen = set(seeds)
            while work:
                k = work.pop()
                want.add(k[0])
                for nxt in graph.get(k, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        work.append(nxt)
        return want
