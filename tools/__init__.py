# tools/ is a package so `python -m tools.ptpu_check` resolves; the
# standalone scripts in here keep working when invoked by path.
