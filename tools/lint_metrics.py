#!/usr/bin/env python
"""DEPRECATED shim — this lint is re-homed as the ``metric-hygiene``
rule of the unified analyzer (``python -m tools.ptpu_check``; see README
"Static analysis").

Kept so the historical CLI keeps working: ``python tools/lint_metrics.py
[root]`` (default: paddle_tpu/), exit 0 = clean / 1 = violations, one
``path:line: message`` per violation.  Both the legacy ``metric-ok:``
marker and the unified ``ptpu-check[metric-hygiene]:`` marker suppress.
"""
from __future__ import annotations

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))   # repo root

from tools.ptpu_check.api import run_check   # noqa: E402


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(_HERE, "..",
                                                      "paddle_tpu")
    root = os.path.abspath(root)
    report, _ = run_check(paths=[root], repo_root=os.path.dirname(root),
                          rule_ids=["metric-hygiene"], use_baseline=False)
    bad = [f for f in report.errors if f.rule == "syntax-error"] + \
        report.new
    for f in bad:
        print(f"{f.path}:{f.line}: {f.message}")
    if bad:
        print(f"\nlint_metrics: {len(bad)} violation(s)")
        return 1
    print("lint_metrics: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
