#!/usr/bin/env python
"""Repo lint: monitor metric hygiene in paddle_tpu/ (ISSUE 5 satellite).

A metrics layer rots in two ways: names drift off the `subsystem/metric`
convention (so dashboards can't group by subsystem and the Prometheus
mapping collides), and labels grow unbounded cardinality (every request
id as a label value = one time series per request = an OOM'd scrape
target).  This lint pins both at the AST level:

1. every ``monitor.counter/gauge/histogram("name", ...)`` call site must
   pass a LITERAL name matching ``subsystem/metric_name``
   (``^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)+$``).  Dynamic names hide from
   grep and from this lint — a genuinely-parameterized registration
   helper documents itself with a ``metric-ok:`` comment on (or right
   above) the line;
2. every ``.labels(...)`` call must use explicit keywords (no
   positional args, no ``**kwargs`` expansion — static bound), at most
   ``MAX_LABELS`` of them, each key matching ``^[a-z][a-z0-9_]*$``.
   The keyword bound keeps the *dimensions* finite; value cardinality
   is a review concern the explicit-keyword rule makes reviewable.

Scope: paddle_tpu/, excluding monitor/__init__.py (the registry itself —
its counter()/gauge()/histogram() signatures take the caller's name).

Usage: python tools/lint_metrics.py [root]     (default: paddle_tpu/)
Exit code 0 = clean, 1 = violations (printed one per line).
"""
from __future__ import annotations

import ast
import os
import re
import sys

MARKER = "metric-ok:"
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)+$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")
MAX_LABELS = 3
METRIC_METHODS = ("counter", "gauge", "histogram")
REGISTRY_NAMES = ("monitor", "m", "_monitor")
SKIP_FILES = (os.path.join("monitor", "__init__.py"),)


def _is_metric_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in METRIC_METHODS:
        return False
    v = f.value
    if isinstance(v, ast.Name) and v.id in REGISTRY_NAMES:
        return True
    if isinstance(v, ast.Attribute) and v.attr == "monitor":
        return True
    return False


def _marked(lines, node) -> bool:
    """metric-ok: on the node's first line or the line above it."""
    i = node.lineno - 1
    window = lines[max(0, i - 1):i + 1]
    return any(MARKER in ln for ln in window)


def check_file(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if _is_metric_call(node):
            if not node.args:
                out.append((path, node.lineno,
                            f"{f.attr}() without a metric name"))
            else:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    if not NAME_RE.match(arg.value):
                        out.append((
                            path, node.lineno,
                            f"metric name {arg.value!r} breaks the "
                            "`subsystem/metric_name` convention "
                            f"({NAME_RE.pattern})"))
                elif not _marked(lines, node):
                    out.append((
                        path, node.lineno,
                        f"dynamic metric name in {f.attr}() — pass a "
                        "literal `subsystem/metric`, or document the "
                        f"helper with `# {MARKER} ...`"))
        elif isinstance(f, ast.Attribute) and f.attr == "labels":
            if _marked(lines, node):
                continue
            if node.args:
                out.append((path, node.lineno,
                            ".labels() takes keywords only "
                            "(labels(kind=...), not labels(value))"))
            kws = node.keywords
            if any(k.arg is None for k in kws):
                out.append((path, node.lineno,
                            ".labels(**dict) hides the label set — "
                            "spell the keywords out, or document with "
                            f"`# {MARKER} ...`"))
            if len(kws) > MAX_LABELS:
                out.append((path, node.lineno,
                            f".labels() with {len(kws)} keys (> "
                            f"{MAX_LABELS}): every key multiplies series "
                            "cardinality"))
            for k in kws:
                if k.arg is not None and not LABEL_RE.match(k.arg):
                    out.append((path, node.lineno,
                                f"label key {k.arg!r} breaks "
                                f"{LABEL_RE.pattern}"))
    return out


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "paddle_tpu")
    root = os.path.abspath(root)
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            if rel in SKIP_FILES:
                continue
            violations.extend(check_file(path))
    for path, lineno, msg in violations:
        rel = os.path.relpath(path, os.path.dirname(root))
        print(f"{rel}:{lineno}: {msg}")
    if violations:
        print(f"\nlint_metrics: {len(violations)} violation(s)")
        return 1
    print("lint_metrics: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
