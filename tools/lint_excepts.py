#!/usr/bin/env python
"""Repo lint: no silently-swallowed failures in paddle_tpu/.

Rejects two patterns (ISSUE 3 satellite — a resilience runtime is only
trustworthy if failures can't vanish):

1. a bare ``except:`` anywhere (catches SystemExit/KeyboardInterrupt —
   it would even eat the preemption handler's exit);
2. ``except Exception:`` / ``except BaseException:`` whose handler body
   is ONLY ``pass``/``...`` — the classic silent swallow.

A site that is genuinely justified (interpreter teardown, best-effort
cosmetic cleanup) stays allowed by carrying the marker ``justified:``
in a comment on the ``except`` line or inside the handler body, e.g.::

    except Exception:  # justified: interpreter teardown — raising in
        # __del__ only prints noise
        pass

The marker forces every swallow to document WHY it is safe; the lint
turns an undocumented one into a CI failure.

Usage: python tools/lint_excepts.py [root]      (default: paddle_tpu/)
Exit code 0 = clean, 1 = violations (printed one per line).
"""
from __future__ import annotations

import ast
import os
import sys

MARKER = "justified:"
BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/... — the exception dies with no trace."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue   # docstring or `...`
        return False
    return True


def _handler_lines(src_lines, handler: ast.ExceptHandler):
    last = handler.lineno
    for n in ast.walk(handler):
        end = getattr(n, "end_lineno", None)
        if end is not None:
            last = max(last, end)
    return src_lines[handler.lineno - 1:last]


def check_file(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        justified = any(MARKER in ln for ln in _handler_lines(lines, node))
        if node.type is None:
            if not justified:
                out.append((path, node.lineno,
                            "bare `except:` (catches SystemExit/"
                            "KeyboardInterrupt) — name the exceptions, or "
                            f"document with `# {MARKER} ...`"))
            continue
        if _is_broad(node) and _swallows(node) and not justified:
            out.append((path, node.lineno,
                        "`except Exception: pass` silently swallows "
                        "failures — narrow the types, handle it, or "
                        f"document with `# {MARKER} ...`"))
    return out


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "paddle_tpu")
    root = os.path.abspath(root)
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(check_file(os.path.join(dirpath, name)))
    for path, lineno, msg in violations:
        rel = os.path.relpath(path, os.path.dirname(root))
        print(f"{rel}:{lineno}: {msg}")
    if violations:
        print(f"\nlint_excepts: {len(violations)} violation(s)")
        return 1
    print("lint_excepts: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
