"""Bench regression gate (reference capability:
tools/check_op_benchmark_result.py + tools/ci_op_benchmark.sh — relative
regression checks against a prior run, no absolute thresholds).

Compares the current bench artifacts against a baseline run:

    python tools/check_bench_regression.py BENCH_r01.json BENCH_r02.json
    python tools/check_bench_regression.py --ladder OLD_LADDER.json BENCH_LADDER.json

Exit 0 = no metric regressed more than --tolerance (default 7%, chosen
above the observed ~±5% tunnel run-to-run variance); exit 1 otherwise.
CPU-smoke fallback lines (tunnel outage) are reported but never gate.
"""
import argparse
import json
import sys


def _entries(path):
    """Yield {metric, value, ...} dicts from either artifact shape:
    driver BENCH_r*.json ({"parsed": {...}}) or BENCH_LADDER.json lists."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = [doc.get("parsed", doc)]
    for entry in doc:
        if entry and "metric" in entry:
            yield entry


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("baseline")
    ap.add_argument("current")
    ap.add_argument("--ladder", action="store_true",
                    help="compat no-op; both artifact shapes auto-detected")
    ap.add_argument("--tolerance", type=float, default=0.07,
                    help="allowed fractional drop per metric (default 7%%)")
    args = ap.parse_args(argv)

    base = {e["metric"]: e for e in _entries(args.baseline)}
    cur = {e["metric"]: e for e in _entries(args.current)}

    failures = []
    for name, b in sorted(base.items()):
        c = cur.get(name)
        if "error" in b or b.get("value", 0) <= 0:
            continue                    # baseline itself failed: nothing to gate
        if "smoke" in name:
            continue                    # CPU fallback line: outage, not perf
        if c is None or "error" in c:
            msg = c.get("error", "missing") if c else "missing"
            print(f"FAIL {name}: current run has no number ({msg})")
            failures.append(name)
            continue
        ratio = c["value"] / b["value"]
        status = "ok" if ratio >= 1.0 - args.tolerance else "FAIL"
        print(f"{status:4s} {name}: {b['value']:.2f} -> {c['value']:.2f} "
              f"({(ratio - 1) * 100:+.1f}%)")
        if status == "FAIL":
            failures.append(name)
    for name in sorted(set(cur) - set(base)):
        print(f"new  {name}: {cur[name].get('value', cur[name].get('error'))}")

    if failures:
        print(f"\n{len(failures)} regression(s) beyond "
              f"{args.tolerance:.0%}: {', '.join(failures)}")
        return 1
    print("\nno regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
