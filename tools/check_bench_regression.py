"""Bench regression gate (reference capability:
tools/check_op_benchmark_result.py + tools/ci_op_benchmark.sh — relative
regression checks against a prior run, no absolute thresholds).

Pairwise mode — compare two bench artifacts:

    python tools/check_bench_regression.py BENCH_r01.json BENCH_r02.json
    python tools/check_bench_regression.py --ladder OLD_LADDER.json BENCH_LADDER.json

History mode (ISSUE 6) — gate the newest run in the persistent ledger
(`BENCH_HISTORY.jsonl`, appended by every bench.py emit) against the
trailing median of comparable prior runs:

    python tools/check_bench_regression.py --history BENCH_HISTORY.jsonl
    python tools/check_bench_regression.py --history BENCH_HISTORY.jsonl \
        --current BENCH_LADDER.json --gate-smoke --tolerance 0.5

"Comparable" means same metric, same host, same backend, backend alive —
a host or backend change starts a fresh lane and NEVER gates (outage and
hardware churn are not regressions).  Fewer than --min-samples priors in
the lane: reported, passes.  Metrics whose name contains "overhead" are
lower-is-better and gate in the opposite direction (the pairwise mode
skips them for exactly that reason).

Exit 0 = no metric regressed more than --tolerance (default 7%, chosen
above the observed ~±5% tunnel run-to-run variance); exit 1 otherwise.
CPU-smoke lines gate only with --gate-smoke (the fast-CI lane, where the
CPU host IS the lane) — without it they are reported but never gate.
"""
import argparse
import json
import statistics
import sys


def _entries(path):
    """Yield {metric, value, ...} dicts from either artifact shape:
    driver BENCH_r*.json ({"parsed": {...}}) or BENCH_LADDER.json lists."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = [doc.get("parsed", doc)]
    for entry in doc:
        if entry and "metric" in entry:
            yield entry


def _ledger_entries(path):
    """Yield ledger records from a BENCH_HISTORY.jsonl file, skipping
    truncated/corrupt lines (a killed bench can leave a partial tail)."""
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                yield rec


def _is_smoke(rec):
    name = rec.get("metric", "")
    return bool(rec.get("cpu_smoke")) or "smoke" in name \
        or "skipped_cpu" in name


def _usable(rec):
    return ("error" not in rec and rec.get("value", 0) > 0
            and not rec.get("backend_unavailable"))


def _age_hours(rec):
    """Hours since the record's ledger timestamp; None when untagged
    (bench artifacts and hand-built test ledgers carry no ts → treated
    as fresh)."""
    ts = rec.get("ts")
    if not ts:
        return None
    import datetime

    try:
        then = datetime.datetime.fromisoformat(ts)
    except ValueError:
        return None
    if then.tzinfo is None:
        # naive ISO stamp (other tooling / hand-built ledgers): assume
        # UTC — bench.py's own stamps always carry an offset
        then = then.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return (now - then).total_seconds() / 3600.0


def check_history(args):
    history = list(_ledger_entries(args.history))
    if not history:
        print(f"history gate: {args.history} is empty — nothing to gate")
        return 0

    if args.current:
        current = [e for e in _entries(args.current) if _usable(e)]
        prior = history
        # bench artifacts (BENCH_LADDER.json / BENCH_r*.json) carry no
        # host/backend tags, but bench.py ledgers every emit — so the
        # artifact's run IS the newest ledger entry for its metric;
        # inherit that entry's lane tags
        newest = {}
        for rec in history:
            newest[rec["metric"]] = rec
        for e in current:
            src = newest.get(e["metric"])
            if src is not None:
                e.setdefault("host", src.get("host"))
                e.setdefault("backend", src.get("backend"))
                e.setdefault("cpu_smoke", src.get("cpu_smoke"))
                # the artifact's run is that newest ledger entry: keep it
                # out of its own comparison lane
                e["_self"] = src
    else:
        # newest ledger entry per metric is "the current run"; everything
        # before it is history
        last_idx = {}
        for i, rec in enumerate(history):
            last_idx[rec["metric"]] = i
        current = [history[i] for i in sorted(last_idx.values())
                   if _usable(history[i])]
        prior = [rec for i, rec in enumerate(history)
                 if i < last_idx.get(rec["metric"], len(history))]

    failures = []
    for cur in current:
        name = cur["metric"]
        age_h = _age_hours(cur)
        if age_h is not None and age_h > args.max_age_hours:
            # the newest ledger entry for this metric was NOT produced by
            # the invocation being gated (a metric last benched days ago
            # must not fail today's unrelated CI run forever)
            print(f"stale {name}: newest run is {age_h:.1f}h old "
                  f"(> {args.max_age_hours:g}h) — not this invocation, "
                  "skipped")
            continue
        if _is_smoke(cur) and not args.gate_smoke:
            print(f"skip {name}: cpu-smoke lane (pass --gate-smoke to "
                  "gate it)")
            continue
        lane = [p for p in prior
                if p["metric"] == name and _usable(p)
                and p is not cur.get("_self")
                and p.get("host") == cur.get("host")
                and p.get("backend") == cur.get("backend")]
        if len(lane) < args.min_samples:
            print(f"new  {name}: {len(lane)} comparable prior run(s) "
                  f"(< {args.min_samples}) — lane too young to gate")
            continue
        window = [p["value"] for p in lane[-args.window:]]
        med = statistics.median(window)
        ratio = cur["value"] / med
        lower_is_better = "overhead" in name
        if lower_is_better:
            bad = ratio > 1.0 + args.tolerance
            arrow = "<=" if not bad else ">"
        else:
            bad = ratio < 1.0 - args.tolerance
            arrow = ">=" if not bad else "<"
        status = "FAIL" if bad else "ok"
        print(f"{status:4s} {name}: {cur['value']:.2f} vs trailing median "
              f"{med:.2f} over {len(window)} run(s) "
              f"({(ratio - 1) * 100:+.1f}% {arrow} "
              f"{'+' if lower_is_better else '-'}{args.tolerance:.0%})")
        if bad:
            failures.append(name)
    if failures:
        print(f"\n{len(failures)} regression(s) vs trailing median beyond "
              f"{args.tolerance:.0%}: {', '.join(failures)}")
        return 1
    print("\nno regressions vs trailing median")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("baseline", nargs="?",
                    help="pairwise mode: baseline artifact")
    ap.add_argument("current", nargs="?",
                    help="pairwise mode: current artifact; history mode: "
                    "optional current artifact (default: newest ledger "
                    "entry per metric)", metavar="current")
    ap.add_argument("--current", dest="current_opt", metavar="ARTIFACT",
                    help="history mode: explicit current-run artifact")
    ap.add_argument("--ladder", action="store_true",
                    help="compat no-op; both artifact shapes auto-detected")
    ap.add_argument("--history", metavar="LEDGER",
                    help="gate against the trailing median of this "
                    "BENCH_HISTORY.jsonl instead of a pairwise baseline")
    ap.add_argument("--window", type=int, default=5,
                    help="trailing runs in the median (default 5)")
    ap.add_argument("--min-samples", type=int, default=3,
                    help="comparable priors required before a lane gates "
                    "(default 3)")
    ap.add_argument("--gate-smoke", action="store_true",
                    help="gate cpu-smoke lanes too (fast-CI on a CPU host)")
    ap.add_argument("--max-age-hours", type=float, default=6.0,
                    help="history mode: skip metrics whose newest ledger "
                    "entry is older than this — only runs the current "
                    "invocation produced should gate it (default 6)")
    ap.add_argument("--tolerance", type=float, default=0.07,
                    help="allowed fractional drop per metric (default 7%%)")
    args = ap.parse_args(argv)

    if args.history:
        if args.current_opt:
            args.current = args.current_opt
        elif args.baseline and not args.current:
            # `--history L CUR.json` reads naturally; the lone positional
            # lands in `baseline`
            args.current = args.baseline
        return check_history(args)
    if not args.baseline or not args.current:
        ap.error("pairwise mode needs BASELINE and CURRENT artifacts "
                 "(or use --history LEDGER)")

    base = {e["metric"]: e for e in _entries(args.baseline)}
    cur = {e["metric"]: e for e in _entries(args.current)}

    failures = []
    for name, b in sorted(base.items()):
        c = cur.get(name)
        if "error" in b or b.get("value", 0) <= 0:
            continue                    # baseline itself failed: nothing to gate
        if "smoke" in name:
            continue                    # CPU fallback line: outage, not perf
        if "overhead" in name:
            continue                    # lower-is-better: history mode gates it
        if c is None or "error" in c:
            msg = c.get("error", "missing") if c else "missing"
            print(f"FAIL {name}: current run has no number ({msg})")
            failures.append(name)
            continue
        ratio = c["value"] / b["value"]
        status = "ok" if ratio >= 1.0 - args.tolerance else "FAIL"
        print(f"{status:4s} {name}: {b['value']:.2f} -> {c['value']:.2f} "
              f"({(ratio - 1) * 100:+.1f}%)")
        if status == "FAIL":
            failures.append(name)
    for name in sorted(set(cur) - set(base)):
        print(f"new  {name}: {cur[name].get('value', cur[name].get('error'))}")

    if failures:
        print(f"\n{len(failures)} regression(s) beyond "
              f"{args.tolerance:.0%}: {', '.join(failures)}")
        return 1
    print("\nno regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
