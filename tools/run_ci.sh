#!/usr/bin/env bash
# CI entry point (reference capability: paddle_build.sh test stages +
# tools/gen_ut_cmakelists.py tier metadata — here: pytest tiers + the
# driver-shaped gates).
#
#   tools/run_ci.sh fast    — "not slow" tier on the virtual 8-device CPU mesh
#                             (includes the resilience suite + ptpu_check)
#   tools/run_ci.sh full    — everything incl. subprocess/example suites
#   tools/run_ci.sh lint    — unified static analyzer only (ptpu_check,
#                             all 12 rules: silent-except, metric-hygiene,
#                             host-sync, donation, lock-discipline,
#                             determinism, wall-clock, resource-leak,
#                             blocking-in-handler, recompile-hazard,
#                             wire-compat, env-flag-drift over
#                             paddle_tpu/ tools/ scripts/; JSON artifact
#                             at /tmp/ptpu_check_report.json)
#   tools/run_ci.sh chaos   — the deterministic network-fault schedule
#                             (ISSUE 18): scripts/chaos_smoke.py under a
#                             fixed PTPU_CHAOS_SEED — router + 4 replica
#                             processes through drop/delay/partition/
#                             garble/stall/SIGKILL, asserting no-hang,
#                             token-identity and zero KV leaks
#   tools/run_ci.sh gates   — driver gates: compile-check entry() + the
#                             8-device multichip dryrun + CPU bench smoke
#   tools/run_ci.sh bench-check OLD.json NEW.json — perf regression gate
#   tools/run_ci.sh bench-history [args] — gate the newest BENCH_HISTORY
#                             ledger entries against their trailing median
set -euo pipefail
cd "$(dirname "$0")/.."

export PTPU_FORCE_PLATFORM="${PTPU_FORCE_PLATFORM:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

case "${1:-fast}" in
  fast)
    # unified static analyzer, INCREMENTAL (ISSUE 14): rules run only
    # on files changed vs ${PTPU_CHECK_BASE:-HEAD} plus their
    # call-graph closure — the fast lane pays ~2 s of parse+graph for a
    # clean tree and seconds for a working diff, instead of the
    # whole-tree rule wall.  `full` and `lint` keep the whole-tree run
    # (all 12 rules), so nothing lands unanalyzed.
    python -m tools.ptpu_check --changed "${PTPU_CHECK_BASE:-HEAD}" \
      --json-out /tmp/ptpu_check_report.json
    # "not slow" includes tests/test_train_stats.py (ISSUE 13: loss-spike
    # EWMA, goodput math, straggler rollup, forensics — subprocess-free)
    # and the serve_smoke --slo leg (ISSUE 16: deadline request ->
    # reqlog event -> kept trace -> exemplar -> fleet-merged burn rate),
    # which rides the EXISTING test_serving.py smoke subprocess — no
    # second engine-compiling process in the fast lane
    python -m pytest tests/ -m "not slow" -q --ignore=tests/test_examples.py
    # perf-history gate, CPU-smoke lane: the headline bench appends this
    # host's run to BENCH_HISTORY.jsonl, then gates against the trailing
    # median of SAME-host same-backend runs (a host change starts a fresh
    # lane — reported, never failed).  Loose 50% tolerance: the CPU smoke
    # config is tiny and shared-host noisy; it catches cliffs, the real
    # lane in `gates` catches percent-level drift on chip hosts.
    python bench.py
    # ragged-vs-bucketed decode A/B (ISSUE 8): its tokens/s lines join
    # the same smoke-lane history gate below
    python bench.py --config ragged_decode
    # router fan-out (ISSUE 17): host-side dispatch throughput over fake
    # in-process replicas — backend-free, so the CPU lane IS the lane;
    # self-asserts sticky routing actually engaged before emitting
    python bench.py --config router_fanout
    python tools/check_bench_regression.py --history BENCH_HISTORY.jsonl \
      --gate-smoke --tolerance 0.50
    ;;
  full)
    python -m tools.ptpu_check --json-out /tmp/ptpu_check_report.json
    # includes the slow tier: tests/test_fleet.py::test_fleet_smoke_script
    # runs scripts/fleet_smoke.py (ISSUE 11 acceptance — 2 engine
    # replicas + aggregator; the fleet fast-tier unit tests ride the
    # "not slow" selection above like every other suite) and
    # tests/test_router.py::test_router_smoke_script runs
    # scripts/router_smoke.py (ISSUE 17 acceptance — router + 4 replica
    # processes: sticky prefix routing, disaggregated prefill/decode
    # handoff, mid-stream SIGKILL failover, all token-identical) and
    # tests/test_chaos.py::test_chaos_smoke_script runs
    # scripts/chaos_smoke.py (ISSUE 18 acceptance — the seeded
    # network-fault schedule, same as the `chaos` lane below) and
    # tests/test_api.py::test_api_smoke_script runs scripts/api_smoke.py
    # (ISSUE 19 acceptance — replica stall behind the API -> 504 inside
    # the deadline, and mid-stream SIGKILL -> failover with the stream
    # finishing token-identical; streams never hang)
    python -m pytest tests/ -q
    ;;
  chaos)
    # seed pinned so the fault schedule's p= rolls replay bit-identically
    # run-to-run (the replay contract itself is unit-pinned in
    # tests/test_chaos.py); override with PTPU_CHAOS_SEED=<n>
    PTPU_CHAOS_SEED="${PTPU_CHAOS_SEED:-7}" JAX_PLATFORMS=cpu \
      python scripts/chaos_smoke.py
    ;;
  lint)
    # whole-tree, all 12 rules (the 5 ISSUE-14 interprocedural rules —
    # resource-leak, blocking-in-handler, recompile-hazard, wire-compat,
    # env-flag-drift — ride the same one-parse-per-file core)
    python -m tools.ptpu_check --json-out /tmp/ptpu_check_report.json
    echo "ptpu_check: JSON artifact at /tmp/ptpu_check_report.json"
    ;;
  gates)
    python - <<'EOF'
import __graft_entry__ as g
fn, args = g.entry()
import jax
print("entry() abstract eval:", jax.eval_shape(fn, *args))
g.dryrun_multichip(8)
print("gates OK")
EOF
    python bench.py
    # ISSUE 12 launch-accounting lane: programs-per-decode-step +
    # padding-waste, self-asserting the 3→5 crossing stays FLAT (lives
    # here, NOT in fast — tier-1 room is scarce at ~790s of 870s)
    python bench.py --config kernel_count
    # ISSUE 15 serving-throughput lanes (same tier-placement logic):
    # cold-vs-hot TTFT for a shared-prefix batch, and steady-state
    # decode-step tokens/s spec-on vs spec-off (min/best-over-steps —
    # whole-generate walls drift >50% on shared hosts)
    python bench.py --config prefix_prefill
    python bench.py --config spec_decode
    # ISSUE 19 API front-door lane: seeded open-loop arrivals at rising
    # QPS through a live ApiServer socket — goodput gates higher-is-
    # better, the *_overhead_* TTFT/TPOT percentiles gate lower-is-better
    python bench.py --config serving_load
    # real-lane history gate: default 7% tolerance, smoke lines skipped
    # (on a chip host the headline is the non-smoke metric and gates;
    # after an outage fallback the smoke line is reported only)
    python tools/check_bench_regression.py --history BENCH_HISTORY.jsonl
    ;;
  bench-check)
    shift
    python tools/check_bench_regression.py "$@"
    ;;
  bench-history)
    shift
    python tools/check_bench_regression.py --history BENCH_HISTORY.jsonl "$@"
    ;;
  *)
    echo "usage: $0 {fast|full|lint|chaos|gates|bench-check OLD NEW|bench-history}" >&2
    exit 2
    ;;
esac
