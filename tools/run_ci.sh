#!/usr/bin/env bash
# CI entry point (reference capability: paddle_build.sh test stages +
# tools/gen_ut_cmakelists.py tier metadata — here: pytest tiers + the
# driver-shaped gates).
#
#   tools/run_ci.sh fast    — "not slow" tier on the virtual 8-device CPU mesh
#                             (includes the resilience suite + repo lints)
#   tools/run_ci.sh full    — everything incl. subprocess/example suites
#   tools/run_ci.sh lint    — repo lints only (no-silent-swallow except
#                             check + metric naming/label-cardinality check)
#   tools/run_ci.sh gates   — driver gates: compile-check entry() + the
#                             8-device multichip dryrun + CPU bench smoke
#   tools/run_ci.sh bench-check OLD.json NEW.json — perf regression gate
set -euo pipefail
cd "$(dirname "$0")/.."

export PTPU_FORCE_PLATFORM="${PTPU_FORCE_PLATFORM:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

case "${1:-fast}" in
  fast)
    python tools/lint_excepts.py
    python tools/lint_metrics.py
    python -m pytest tests/ -m "not slow" -q --ignore=tests/test_examples.py
    ;;
  full)
    python tools/lint_excepts.py
    python tools/lint_metrics.py
    python -m pytest tests/ -q
    ;;
  lint)
    python tools/lint_excepts.py
    python tools/lint_metrics.py
    ;;
  gates)
    python - <<'EOF'
import __graft_entry__ as g
fn, args = g.entry()
import jax
print("entry() abstract eval:", jax.eval_shape(fn, *args))
g.dryrun_multichip(8)
print("gates OK")
EOF
    python bench.py
    ;;
  bench-check)
    shift
    python tools/check_bench_regression.py "$@"
    ;;
  *)
    echo "usage: $0 {fast|full|lint|gates|bench-check OLD NEW}" >&2
    exit 2
    ;;
esac
