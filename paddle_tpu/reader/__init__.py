"""Legacy reader decorators (reference: python/paddle/reader/decorator.py —
cache/shuffle/chain/compose/buffered/firstn/map_readers/xmap_readers/
multiprocess_reader, plus python/paddle/batch.py `paddle.batch`).

These are host-side generator combinators; nothing device-specific. The
modern path is paddle.io.DataLoader — this module exists so reference
training scripts using reader pipelines run unchanged.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "multiprocess_reader", "batch"]


def cache(reader):
    """Cache the reader's full output in memory on first pass
    (decorator.py:45)."""
    all_data = []
    filled = [False]

    def rd():
        if not filled[0]:
            for item in reader():
                all_data.append(item)
                yield item
            filled[0] = True
        else:
            yield from all_data

    return rd


def map_readers(func, *readers):
    """Yield func(*items) zipped across readers (decorator.py:85)."""

    def rd():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return rd


def shuffle(reader, buf_size):
    """Buffered shuffle (decorator.py:127): fill a buf_size window,
    shuffle it, drain."""

    def rd():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                # ptpu-check[determinism]: reference-API contract —
                # decorator.py's shuffle uses the global stream; callers
                # seed `random` for reproducible order (test_examples does)
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            # ptpu-check[determinism]: same contract as above
            _random.shuffle(buf)
            yield from buf

    return rd


def chain(*readers):
    """Concatenate readers back to back (decorator.py:176)."""

    def rd():
        for r in readers:
            yield from r()

    return rd


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (decorator.py:241).
    check_alignment=True (default) raises if lengths mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def _flatten(item):
        if isinstance(item, tuple):
            return item
        return (item,)

    def rd():
        its = [r() for r in readers]
        if check_alignment:
            for items in zip(*its):
                yield sum((_flatten(i) for i in items), ())
            for it in its:
                try:
                    next(it)
                except StopIteration:
                    continue
                raise ValueError("readers have different lengths "
                                 "(check_alignment=True)")
        else:
            for items in itertools.zip_longest(*its):
                yield sum((_flatten(i) for i in items if i is not None), ())

    return rd


def buffered(reader, size):
    """Prefetch up to `size` items on a background thread
    (decorator.py:299)."""

    def rd():
        q = _queue.Queue(maxsize=size)
        end = object()
        err = []

        def fill():
            try:
                for item in reader():
                    q.put(item)
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                if err:
                    raise err[0]
                return
            yield item

    return rd


def firstn(reader, n):
    """First n items (decorator.py:361)."""

    def rd():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return rd


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker THREADS (decorator.py:406 uses
    threads too — the GIL is released in IO/numpy mappers). order=True
    preserves input order."""

    def rd():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        end = object()
        err = []

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            try:
                while True:
                    # ptpu-check[blocking-in-handler]: sentinel-terminated
                    # consumer — feed() always enqueues one `end` per
                    # worker, so this get() is woken on every shutdown
                    # path; a timeout would only add spurious wakeups
                    got = in_q.get()
                    if got is end:
                        break
                    i, item = got
                    out_q.put((i, mapper(item)))
            except BaseException as e:
                err.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        done = 0
        hold = {}
        want = 0
        while done < process_num:
            got = out_q.get()
            if got is end:
                done += 1
                continue
            i, item = got
            if not order:
                yield item
            else:
                hold[i] = item
                while want in hold:
                    yield hold.pop(want)
                    want += 1
        if err:
            raise err[0]
        if order:
            for i in sorted(hold):
                yield hold[i]

    return rd


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers from worker threads (decorator.py:502;
    fork-based processes don't mix with an initialized XLA runtime, so the
    TPU build uses threads — same API, same interleaving semantics)."""

    def rd():
        q = _queue.Queue(queue_size)
        end = object()

        def run(r):
            try:
                for item in r():
                    q.put(item)
            finally:
                q.put(end)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        done = 0
        while done < len(readers):
            item = q.get()
            if item is end:
                done += 1
                continue
            yield item

    return rd


def batch(reader, batch_size, drop_last=False):
    """paddle.batch (reference python/paddle/batch.py:18): group a sample
    reader into lists of batch_size samples."""

    def rd():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return rd
