"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
ModelCheckpoint:551, LRScheduler:616, EarlyStopping:716, VisualDL:880)."""
from __future__ import annotations

import numbers
import os
import time
import warnings

import numpy as np

from .progressbar import ProgressBar

__all__ = [
    "Callback",
    "ProgBarLogger",
    "ModelCheckpoint",
    "LRScheduler",
    "EarlyStopping",
    "VisualDL",
    "ReduceLROnPlateau",
    "config_callbacks",
]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch loss/metric console logging."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.train_progbar = ProgressBar(num=self.steps, verbose=self.verbose)
        self.train_step = 0

    def _metric_items(self, logs):
        out = []
        for k in self.params.get("metrics", []):
            if k in (logs or {}):
                v = logs[k]
                if isinstance(v, numbers.Number):
                    v = float(v)
                out.append((k, v))
        return out

    def on_train_batch_end(self, step, logs=None):
        self.train_step = step + 1
        if self.verbose and self.train_step % self.log_freq == 0:
            self.train_progbar.update(self.train_step, self._metric_items(logs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self.train_progbar.update(self.train_step, self._metric_items(logs))

    def on_eval_begin(self, logs=None):
        self.eval_steps = (logs or {}).get("steps")
        self.eval_progbar = ProgressBar(num=self.eval_steps, verbose=self.verbose)
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        if self.verbose and (step + 1) % self.log_freq == 0:
            self.eval_progbar.update(step + 1, self._metric_items(logs))

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = self._metric_items(logs)
            print("Eval samples done - " + ", ".join(f"{k}={v}" for k, v in items))


class ModelCheckpoint(Callback):
    """Save model + optimizer every `save_freq` epochs to `save_dir`."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Step the optimizer's LR scheduler each epoch (or batch)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"EarlyStopping mode {mode} unknown, using auto")
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline
        else:
            self.best_value = np.inf if self.monitor_op == np.less else -np.inf
        self.best_weights = None

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None:
                save_dir = self.params.get("save_dir")
                if save_dir:
                    self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Early stopping: monitored {self.monitor} did not improve")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = lambda a, b: np.less(a, b - self.min_delta)
            self.best = np.inf
        else:
            self.monitor_op = lambda a, b: np.greater(a, b + self.min_delta)
            self.best = -np.inf
        self.cooldown_counter = 0
        self.wait = 0

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    old = opt.get_lr()
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old} -> {new}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging to a directory as TSV (the reference logs to VisualDL;
    that dashboard isn't available here, so the same scalars land in
    `log_dir/scalars.tsv` for any plotting frontend)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None
        self.epoch = 0

    def _write(self, tag, step, value):
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.tsv"), "a")
        self._fh.write(f"{time.time()}\t{tag}\t{step}\t{value}\n")
        self._fh.flush()

    def on_epoch_end(self, epoch, logs=None):
        self.epoch = epoch
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                self._write(f"train/{k}", epoch, v)

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                self._write(f"eval/{k}", self.epoch, v)

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()
            self._fh = None


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cb_list = CallbackList(cbks)
    cb_list.set_model(model)
    params = {
        "batch_size": batch_size,
        "epochs": epochs,
        "steps": steps,
        "verbose": verbose,
        "metrics": metrics or [],
        "save_dir": save_dir,
    }
    cb_list.set_params(params)
    return cb_list
