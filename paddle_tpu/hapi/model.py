"""High-level `Model` API (reference: python/paddle/hapi/model.py —
Model:1004, fit:1696, evaluate/predict, save/load, summary).

TPU-native notes: the reference switches between a dygraph adapter and a
static-graph adapter; here eager execution *is* jax under the hood and the
performance path is whole-graph jit (`paddle_tpu.jit.compile`), which
`prepare(..., jit_compile=True)` turns on for train/eval batches.
"""
from __future__ import annotations

import os
import time
import warnings
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework.io_ import save as _save, load as _load
from ..io import DataLoader, Dataset
from ..metric import Metric
from .. import monitor
from ..monitor import perf as mperf
from ..monitor import train as mtrain
from ..nn.layer import Layer
from .callbacks import config_callbacks

__all__ = ["Model", "summary"]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


def _batch_examples(ins) -> int:
    """Leading-dim example count of a batch's first input — shape
    metadata only, never a device transfer."""
    if not ins:
        return 0
    shape = getattr(ins[0], "shape", None)
    if shape is not None and len(shape):
        return int(shape[0])
    try:
        return len(ins[0])
    except TypeError:
        return 0


class Model:
    """Layer wrapper with train/eval/predict loops and callback hooks."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit_compile = False
        self._compiled_train = None
        self._compiled_eval = None
        self.stop_training = False

    # -- configuration -----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile=False):
        self._optimizer = optimizer
        if loss is not None and not isinstance(loss, Layer) and not callable(loss):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle_tpu.metric.Metric")
        if amp_configs is not None:
            warnings.warn("amp_configs: use amp.auto_cast/GradScaler directly; ignored here")
        self._jit_compile = jit_compile
        self._compiled_train = None
        self._compiled_eval = None

    def parameters(self, include_sublayers=True):
        return self.network.parameters(include_sublayers=include_sublayers)

    # -- single-batch ops --------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outputs = _to_list(outputs)
        labels = _to_list(labels)
        if self._loss is None:
            raise RuntimeError("loss not set; call prepare(loss=...) first")
        return self._loss(*(outputs + labels))

    def _metric_update(self, outputs, labels):
        outputs = _to_list(outputs)
        labels = _to_list(labels)
        results = {}
        for m in self._metrics:
            computed = m.compute(*(outputs + labels))
            if not isinstance(computed, (list, tuple)):
                computed = [computed]
            r = m.update(*computed)
            results[m.name()] = r
        return results

    def _split_batch(self, batch):
        """Single source of truth for the inputs/labels split of a loader
        batch: the `labels` spec wins; otherwise a model prepared with a
        loss treats the last element as the label."""
        batch = _to_list(batch)
        if self._labels:
            n_lab = min(len(self._labels), len(batch) - 1)
        elif self._loss is not None and len(batch) > 1:
            n_lab = 1
        else:
            n_lab = 0
        n_in = len(batch) - n_lab
        return batch[:n_in], batch[n_in:]

    def _train_step(self, inputs, labels):
        # perf mode (PTPU_PERF=1): the eager train step reports synced
        # forward/backward/optimizer segments to the attribution table;
        # with the gate off each `segment` is one module-global read.
        perf_on = mperf.enabled()
        with mperf.segment("train", "forward") as s:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
            s.sync(loss)
        with mperf.segment("train", "backward") as s:
            loss.backward()
            if perf_on:
                s.sync([p.grad for p in self.network.parameters()
                        if p.grad is not None])
        with mperf.segment("train", "optimizer") as s:
            self._optimizer.step()
            if perf_on:
                s.sync(list(self.network.parameters()))
            self._optimizer.clear_grad()
        return loss, outputs, labels

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        if self._jit_compile:
            if self._metrics and not getattr(self, "_warned_jit_metrics", False):
                warnings.warn(
                    "metrics are not updated on the jit_compile train path "
                    "(only loss is returned); evaluate() still computes them"
                )
                self._warned_jit_metrics = True
            if self._compiled_train is None:
                from .. import jit

                self._compiled_train = jit.compile(
                    self._train_step_fn_for_jit(len(inputs)),
                    models=(self.network,),
                    optimizers=(self._optimizer,),
                )
            loss = self._compiled_train(*(inputs + labels))
            outputs = None
        else:
            loss, outputs, labels = self._train_step(inputs, labels)
        logs = {"loss": float(loss.item() if isinstance(loss, Tensor) else loss)}
        if outputs is not None and self._metrics:
            logs.update(self._metric_update(outputs, labels))
        return logs

    def _train_step_fn_for_jit(self, n_in):
        def step(*data):
            inputs, labels = list(data[:n_in]), list(data[n_in:])
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
            return loss

        return step

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import no_grad

        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        with no_grad():
            outputs = self.network(*inputs)
            logs = {}
            if self._loss is not None and labels:
                loss = self._compute_loss(outputs, labels)
                logs["loss"] = float(loss.item())
            logs.update(self._metric_update(outputs, labels))
        return logs

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad

        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        with no_grad():
            outputs = self.network(*inputs)
        return [o.numpy() for o in _to_list(outputs)]

    # -- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last=False):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        assert train_data is not None, "train_data must be given"
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False, num_workers)
        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        metric_names = ["loss"] + [m.name() for m in self._metrics]
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=metric_names,
        )
        self.stop_training = False
        cbks.on_train_begin()
        history = []
        # input-pipeline goodput (ISSUE 13 wing c): time blocked on the
        # reader vs in the train step — the training twin of
        # serving/goodput_tokens_per_s.  With monitor off the loop runs
        # exactly as before (no meter, no perf_counter calls).
        meter = mtrain.GoodputMeter() if monitor.enabled() else None
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            step = 0
            it = iter(train_loader)
            while True:
                if meter is not None:
                    t0 = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    meter.wait(time.perf_counter() - t0)
                else:
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                t1 = time.perf_counter() if meter is not None else 0.0
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                logs = self.train_batch(ins, labs or None)
                cbks.on_train_batch_end(step, logs)
                if meter is not None:
                    # the step bucket spans batch-acquired → loop bottom
                    # (split, callbacks included), so wait + step really
                    # is the TOTAL loop wall the goodput divides by; and
                    # train_batch floats the loss, so the wall includes
                    # the device step, not just its dispatch
                    meter.step(time.perf_counter() - t1,
                               examples=_batch_examples(ins))
                step += 1
                if self.stop_training:
                    break
            for m in self._metrics:
                logs[m.name()] = m.accumulate()
            cbks.on_epoch_end(epoch, logs)
            history.append(dict(logs))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                history[-1].update({f"eval_{k}": v for k, v in eval_logs.items()})
            if self.stop_training:
                break
        cbks.on_train_end(logs if history else {})
        return history

    def _run_eval(self, loader, cbks):
        steps = None
        try:
            steps = len(loader)
        except TypeError:
            pass
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({"steps": steps})
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            logs = self.eval_batch(ins, labs or None)
            if "loss" in logs:
                losses.append(logs["loss"])
            cbks.on_eval_batch_end(step, logs)
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        if losses:
            logs["loss"] = float(np.mean(losses))
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, log_freq=log_freq,
                                verbose=verbose,
                                metrics=["loss"] + [m.name() for m in self._metrics])
        return self._run_eval(loader, cbks)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose, metrics=[])
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            # datasets that yield (input, label) pairs: feed inputs only.
            # With no loss/labels spec there is nothing to split on — an
            # unprepared model on a labeled dataset needs an inputs spec.
            ins, _ = self._split_batch(batch)
            if self._inputs:
                ins = ins[: len(self._inputs)]
            out = self.predict_batch(ins)
            outputs.append(out)
            cbks.on_predict_batch_end(step, {})
        cbks.on_predict_end()
        # transpose to per-output lists
        n_out = len(outputs[0]) if outputs else 0
        result = [[o[i] for o in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        return result

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        params = _load(path + ".pdparams")
        if skip_mismatch:
            own = self.network.state_dict()
            dropped = [k for k, v in params.items()
                       if k not in own or tuple(own[k].shape) != tuple(v.shape)]
            for k in dropped:
                warnings.warn(f"load(skip_mismatch=True): skipping {k}")
                params.pop(k)
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Layer-tree summary with parameter counts and (when an input is given)
    per-layer output shapes (reference: python/paddle/hapi/model_summary.py)."""
    rows = []
    hooks = []
    shapes = {}

    def make_hook(key):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            if isinstance(out, Tensor):
                shapes[key] = list(out.shape)

        return hook

    named = list(net.named_sublayers(include_self=True))
    if input is None and input_size is not None:
        sizes = input_size if isinstance(input_size, list) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        input = [Tensor(np.zeros(s, dtype=np.dtype(d or "float32"))) for s, d in zip(sizes, dts)]
        input = input[0] if len(input) == 1 else input
    if input is not None:
        for key, layer in named:
            hooks.append(layer.register_forward_post_hook(make_hook(key)))
        from ..autograd import no_grad

        with no_grad():
            net(*(_to_list(input)))
        for h in hooks:
            h.remove()

    total, trainable = 0, 0
    for key, layer in named:
        own = [p for _, p in layer.named_parameters(include_sublayers=False)]
        n = sum(int(np.prod(p.shape)) for p in own)
        rows.append((key or net.__class__.__name__, layer.__class__.__name__,
                     shapes.get(key), n))
    for p in net.parameters():
        n = int(np.prod(p.shape))
        total += n
        if getattr(p, "trainable", True):
            trainable += n

    lines = [f"{'Layer':40s} {'Type':24s} {'Output Shape':20s} {'Param #':>10s}"]
    lines.append("-" * 98)
    for name, cls, shape, n in rows:
        lines.append(f"{name:40s} {cls:24s} {str(shape or '-'):20s} {n:>10d}")
    lines.append("-" * 98)
    lines.append(f"Total params: {total}")
    lines.append(f"Trainable params: {trainable}")
    lines.append(f"Non-trainable params: {total - trainable}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
