"""High-level training API (reference: python/paddle/hapi/)."""
from .model import Model, summary
from . import callbacks

__all__ = ["Model", "summary", "callbacks"]
