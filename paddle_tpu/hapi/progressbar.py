"""Minimal terminal progress meter for hapi fit loops
(reference: python/paddle/hapi/progressbar.py)."""
from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, stream=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._stream = stream
        # per-step timing is elapsed math -> perf_counter, not wall clock
        self._start = time.perf_counter()
        self._last_update = 0

    def _format_values(self, values):
        parts = []
        for k, v in values:
            if isinstance(v, (float,)):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple)):
                parts.append(f"{k}: " + ",".join(f"{x:.4f}" for x in v))
            else:
                parts.append(f"{k}: {v}")
        return " - ".join(parts)

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        now = time.perf_counter()
        msg = self._format_values(values or [])
        if self._num:
            prefix = f"step {current_num}/{self._num}"
        else:
            prefix = f"step {current_num}"
        elapsed = now - self._start
        per = elapsed / max(current_num, 1)
        line = f"{prefix} - {per*1000:.0f}ms/step - {msg}"
        if self._verbose == 1:
            self._stream.write("\r" + line)
            if self._num and current_num >= self._num:
                self._stream.write("\n")
            self._stream.flush()
        elif self._verbose == 2:
            self._stream.write(line + "\n")
            self._stream.flush()
        self._last_update = now
