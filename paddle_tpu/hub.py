"""Model hub (reference: python/paddle/hapi/hub.py — paddle.hub.list/help/
load from github/gitee/local). Zero-egress environment: the local source is
fully supported; remote sources raise with guidance."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise ValueError(
            f"source {source!r} unavailable in this environment (no network "
            f"egress); use source='local' with a checked-out repo dir")


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [name for name in dir(mod)
            if callable(getattr(mod, name)) and not name.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"model {model!r} not in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model)(**kwargs)
