"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import datasets
from . import transforms
from . import models
from . import ops

__all__ = ["datasets", "transforms", "models", "ops"]
