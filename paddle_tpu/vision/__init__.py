"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import datasets
from . import transforms
from . import models
from . import ops

__all__ = ["datasets", "transforms", "models", "ops"]

_image_backend = "pil"


def set_image_backend(backend):
    """Default decode backend for datasets (reference
    vision/image.py set_image_backend): 'pil' or 'cv2' ('cv2' is accepted
    and mapped to PIL here — no OpenCV dependency on this stack)."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (reference vision/image.py image_load)."""
    from PIL import Image

    img = Image.open(path)
    if (backend or _image_backend) in ("cv2", "tensor"):
        import numpy as _np

        return _np.asarray(img)
    return img


__all__ += ["set_image_backend", "get_image_backend", "image_load"]
