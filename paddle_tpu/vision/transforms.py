"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy-based, CHW float arrays."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomRotation", "BrightnessTransform", "ContrastTransform",
    "to_tensor", "normalize", "resize", "hflip", "vflip",
    "BaseTransform", "Grayscale", "ColorJitter", "HueTransform",
    "SaturationTransform", "RandomAffine", "RandomErasing",
    "RandomPerspective", "RandomResizedCrop", "adjust_brightness",
    "adjust_contrast", "adjust_hue", "adjust_saturation", "affine",
    "center_crop", "crop", "erase", "pad", "perspective", "rotate",
    "to_grayscale",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        return img[None]
    if img.ndim == 3 and img.shape[-1] in (1, 3, 4) and img.shape[0] not in (1, 3, 4):
        return img.transpose(2, 0, 1)
    return img


def to_tensor(img, data_format="CHW"):
    arr = _chw(img).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    return arr


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
    std = np.asarray(std, np.float32).reshape(-1, 1, 1)
    return (arr - mean) / std


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        c = arr.shape[0]
        mean = np.asarray(self.mean[:c] if len(self.mean) >= c else self.mean * c, np.float32).reshape(-1, 1, 1)
        std = np.asarray(self.std[:c] if len(self.std) >= c else self.std * c, np.float32).reshape(-1, 1, 1)
        return (arr - mean) / std


def resize(img, size, interpolation="bilinear"):
    arr = _chw(np.asarray(img)).astype(np.float32)
    c, h, w = arr.shape
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, :, None]
    wx = (xs - x0)[None, None, :]
    a = arr[:, y0][:, :, x0]
    b = arr[:, y0][:, :, x1]
    cta = arr[:, y1][:, :, x0]
    d = arr[:, y1][:, :, x1]
    return a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx + cta * wy * (1 - wx) + d * wy * wx


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def __call__(self, img):
        return resize(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        c, h, w = arr.shape
        th, tw = self.size
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[:, i : i + th, j : j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        c, h, w = arr.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[:, i : i + th, j : j + tw]


def hflip(img):
    return np.asarray(img)[..., ::-1].copy()


def vflip(img):
    arr = np.asarray(img)
    return arr[..., ::-1, :].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(arr, ((0, 0), (p[1], p[3]), (p[0], p[2])), constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def __call__(self, img):
        import math

        arr = _chw(np.asarray(img)).astype(np.float32)
        angle = math.radians(pyrandom.uniform(*self.degrees))
        c, h, w = arr.shape
        cy, cx = (h - 1) / 2, (w - 1) / 2
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * math.cos(angle) - (xx - cx) * math.sin(angle)
        xs = cx + (yy - cy) * math.sin(angle) + (xx - cx) * math.cos(angle)
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        valid = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
        out = arr[:, yi, xi] * valid[None]
        return out


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        f = 1 + pyrandom.uniform(-self.value, self.value)
        return np.asarray(img, np.float32) * f


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        f = 1 + pyrandom.uniform(-self.value, self.value)
        mean = arr.mean()
        return (arr - mean) * f + mean


# ---------------------------------------------------------------------------
# Long-tail transforms (reference: vision/transforms/transforms.py +
# functional.py — color jitter family, geometric warps, erasing).
# Host-side numpy on CHW arrays, like the rest of this module: transforms
# run in DataLoader workers; the device sees the collated batch.
# ---------------------------------------------------------------------------

class BaseTransform:
    """Transform base with the reference's keys-dispatch contract
    (transforms.py BaseTransform): subclasses implement _apply_image
    (and optionally _apply_{boxes,mask,...}); __call__ routes inputs by
    self.keys."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        outs = []
        for i, data in enumerate(inputs):
            # inputs beyond len(keys) pass through unchanged (reference
            # BaseTransform contract — labels survive image-only keys)
            key = self.keys[i] if i < len(self.keys) else None
            fn = getattr(self, f"_apply_{key}", None) if key else None
            outs.append(fn(data) if fn else data)
        return tuple(outs)


def crop(img, top, left, height, width):
    return _chw(np.asarray(img))[:, top:top + height, left:left + width]


def center_crop(img, output_size):
    size = ((output_size, output_size) if isinstance(output_size, int)
            else tuple(output_size))
    return CenterCrop(size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    p = [padding] * 4 if isinstance(padding, int) else list(padding)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    arr = _chw(np.asarray(img))
    # reference convention: (left, top, right, bottom)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(arr, ((0, 0), (p[1], p[3]), (p[0], p[2])), mode=mode, **kw)


def adjust_brightness(img, brightness_factor):
    arr = _chw(np.asarray(img)).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi).astype(
        np.asarray(img).dtype if np.asarray(img).dtype == np.uint8 else np.float32)


def adjust_contrast(img, contrast_factor):
    arr = _chw(np.asarray(img)).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    mean = arr.mean()
    return np.clip(mean + contrast_factor * (arr - mean), 0, hi).astype(np.float32)


def _rgb_to_hsv(arr):
    r, g, b = arr[0], arr[1], arr[2]
    maxc = np.max(arr[:3], 0)
    minc = np.min(arr[:3], 0)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-8), 0)
    rc = (maxc - r) / np.maximum(d, 1e-8)
    gc = (maxc - g) / np.maximum(d, 1e-8)
    bc = (maxc - b) / np.maximum(d, 1e-8)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(d == 0, 0.0, h)
    return (h / 6.0) % 1.0, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b])


def adjust_hue(img, hue_factor):
    assert -0.5 <= hue_factor <= 0.5, "hue_factor must be in [-0.5, 0.5]"
    arr = _chw(np.asarray(img)).astype(np.float32)
    scale = 255.0 if arr.max() > 1.5 else 1.0
    h, s, v = _rgb_to_hsv(arr / scale)
    h = (h + hue_factor) % 1.0
    return (_hsv_to_rgb(h, s, v) * scale).astype(np.float32)


def adjust_saturation(img, saturation_factor):
    arr = _chw(np.asarray(img)).astype(np.float32)
    gray = arr[:3].mean(0, keepdims=True)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(gray + saturation_factor * (arr - gray), 0, hi).astype(np.float32)


def to_grayscale(img, num_output_channels=1):
    arr = _chw(np.asarray(img)).astype(np.float32)
    w = np.array([0.299, 0.587, 0.114], np.float32).reshape(3, 1, 1)
    gray = (arr[:3] * w).sum(0, keepdims=True)
    return np.repeat(gray, num_output_channels, 0)


def erase(img, i, j, h, w, v, inplace=False):
    arr = _chw(np.asarray(img))
    out = arr if inplace else arr.copy()
    out[:, i:i + h, j:j + w] = v
    return out


def _affine_grid_sample(arr, matrix, out_shape=None, fill=0):
    """Inverse-warp sampling with bilinear interpolation: out(y, x) =
    in(M @ [x, y, 1]). matrix: [2, 3] inverse affine map; out-of-bounds
    samples take `fill`."""
    c, h, w = arr.shape
    oh, ow = out_shape or (h, w)
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    sx = matrix[0, 0] * xs + matrix[0, 1] * ys + matrix[0, 2]
    sy = matrix[1, 0] * xs + matrix[1, 1] * ys + matrix[1, 2]
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx = sx - x0
    wy = sy - y0
    valid = (sx > -1) & (sx < w) & (sy > -1) & (sy < h)

    def at(yy, xx):
        yc = np.clip(yy, 0, h - 1)
        xc = np.clip(xx, 0, w - 1)
        return arr[:, yc, xc]

    out = (at(y0, x0) * (1 - wy) * (1 - wx) + at(y0, x0 + 1) * (1 - wy) * wx
           + at(y0 + 1, x0) * wy * (1 - wx) + at(y0 + 1, x0 + 1) * wy * wx)
    return np.where(valid, out, np.float32(fill)).astype(np.float32)


def _affine_matrix(angle, translate, scale, shear, center):
    a = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # forward map: T(center) R(angle) Shear Scale T(-center) + translate
    rot = np.array([[np.cos(a + sy), -np.sin(a + sx)],
                    [np.sin(a + sy), np.cos(a + sx)]]) * scale
    m = np.eye(3)
    m[:2, :2] = rot
    m[0, 2] = cx + tx - rot[0, 0] * cx - rot[0, 1] * cy
    m[1, 2] = cy + ty - rot[1, 0] * cx - rot[1, 1] * cy
    return np.linalg.inv(m)[:2]


def affine(img, angle, translate, scale, shear, interpolation="bilinear",
           fill=0, center=None):
    arr = _chw(np.asarray(img)).astype(np.float32)
    c, h, w = arr.shape
    if isinstance(shear, (int, float)):
        shear = (shear, 0.0)
    center = center or ((w - 1) / 2, (h - 1) / 2)
    return _affine_grid_sample(arr, _affine_matrix(angle, translate, scale,
                                                   shear, center), fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _chw(np.asarray(img)).astype(np.float32)
    c, h, w = arr.shape
    if not expand:
        return affine(arr, angle, (0, 0), 1.0, (0.0, 0.0), center=center,
                      fill=fill)
    # expand: output canvas holds the whole rotated image (reference
    # functional rotate expand=True)
    a = np.deg2rad(angle)
    ow = int(np.ceil(abs(w * np.cos(a)) + abs(h * np.sin(a))))
    oh = int(np.ceil(abs(w * np.sin(a)) + abs(h * np.cos(a))))
    cin = ((w - 1) / 2, (h - 1) / 2)
    m = _affine_matrix(angle, (0, 0), 1.0, (0.0, 0.0), cin)
    # shift output coords so the output center maps to the input center
    shift = np.eye(3)
    shift[0, 2] = (w - ow) / 2
    shift[1, 2] = (h - oh) / 2
    m3 = np.vstack([m, [0, 0, 1]]) @ shift
    return _affine_grid_sample(arr, m3[:2], out_shape=(oh, ow), fill=fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Projective warp from 4 point pairs (reference functional
    perspective): solve the homography, inverse-sample."""
    arr = _chw(np.asarray(img)).astype(np.float32)
    A, b = [], []
    # solve forward homography end -> start (inverse sampling map)
    for (xs, ys), (xd, yd) in zip(startpoints, endpoints):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
        b += [xs, ys]
    hvec = np.linalg.lstsq(np.asarray(A, np.float64),
                           np.asarray(b, np.float64), rcond=None)[0]
    Hm = np.append(hvec, 1.0).reshape(3, 3)
    c, h, w = arr.shape
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float32),
                         np.arange(w, dtype=np.float32), indexing="ij")
    denom = Hm[2, 0] * xs + Hm[2, 1] * ys + Hm[2, 2]
    sx = (Hm[0, 0] * xs + Hm[0, 1] * ys + Hm[0, 2]) / denom
    sy = (Hm[1, 0] * xs + Hm[1, 1] * ys + Hm[1, 2]) / denom
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx, wy = sx - x0, sy - y0
    valid = (sx > -1) & (sx < w) & (sy > -1) & (sy < h)

    def at(yy, xx):
        return arr[:, np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)]

    out = (at(y0, x0) * (1 - wy) * (1 - wx) + at(y0, x0 + 1) * (1 - wy) * wx
           + at(y0 + 1, x0) * wy * (1 - wx) + at(y0 + 1, x0 + 1) * wy * wx)
    return np.where(valid, out, np.float32(fill)).astype(np.float32)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _chw(np.asarray(img))
        f = pyrandom.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _chw(np.asarray(img))
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        if not 0 <= hue <= 0.5:
            raise ValueError("hue must be in [0, 0.5]")
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = pyrandom.uniform(max(0, 1 - self.brightness), 1 + self.brightness)
            ops.append(lambda a: adjust_brightness(a, f))
        if self.contrast:
            g = pyrandom.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
            ops.append(lambda a: adjust_contrast(a, g))
        if self.saturation:
            s = pyrandom.uniform(max(0, 1 - self.saturation), 1 + self.saturation)
            ops.append(lambda a: adjust_saturation(a, s))
        if self.hue:
            hf = pyrandom.uniform(-self.hue, self.hue)
            ops.append(lambda a: adjust_hue(a, hf))
        pyrandom.shuffle(ops)
        out = _chw(np.asarray(img))
        for op in ops:
            out = op(out)
        return out


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _chw(np.asarray(img))
        c, h, w = arr.shape
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = np.exp(pyrandom.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = pyrandom.randint(0, h - th)
                j = pyrandom.randint(0, w - tw)
                patch = arr[:, i:i + th, j:j + tw]
                return resize(patch, self.size, self.interpolation)
        return resize(CenterCrop(min(h, w))(arr), self.size,
                      self.interpolation)


class RandomErasing(BaseTransform):
    """Random rectangle erasing (reference RandomErasing / arXiv
    1708.04896)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = _chw(np.asarray(img))
        if pyrandom.random() > self.prob:
            return arr
        c, h, w = arr.shape
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = pyrandom.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = pyrandom.randint(0, h - eh)
                j = pyrandom.randint(0, w - ew)
                if self.value == "random":
                    # per-pixel noise in the image's value range
                    hi = 255.0 if arr.max() > 1.5 else 1.0
                    v = (np.random.rand(c, eh, ew) * hi).astype(arr.dtype)
                else:
                    v = self.value
                return erase(arr, i, j, eh, ew, v, self.inplace)
        return arr


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees) if isinstance(degrees, (int, float))
                        else tuple(degrees))
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.center = center

    def _apply_image(self, img):
        arr = _chw(np.asarray(img))
        c, h, w = arr.shape
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        sc = pyrandom.uniform(*self.scale) if self.scale else 1.0
        if isinstance(self.shear, (list, tuple)):
            sh = pyrandom.uniform(self.shear[0], self.shear[1])
        elif self.shear:
            sh = pyrandom.uniform(-self.shear, self.shear)
        else:
            sh = 0.0
        return affine(arr, angle, (tx, ty), sc, (sh, 0.0), center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale

    def _apply_image(self, img):
        arr = _chw(np.asarray(img))
        if pyrandom.random() > self.prob:
            return arr
        c, h, w = arr.shape
        d = self.distortion_scale
        dx = int(d * w / 2)
        dy = int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(pyrandom.randint(0, dx), pyrandom.randint(0, dy)),
               (w - 1 - pyrandom.randint(0, dx), pyrandom.randint(0, dy)),
               (w - 1 - pyrandom.randint(0, dx), h - 1 - pyrandom.randint(0, dy)),
               (pyrandom.randint(0, dx), h - 1 - pyrandom.randint(0, dy))]
        return perspective(arr, start, end)
