"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy-based, CHW float arrays."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomRotation", "BrightnessTransform", "ContrastTransform",
    "to_tensor", "normalize", "resize", "hflip", "vflip",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        return img[None]
    if img.ndim == 3 and img.shape[-1] in (1, 3, 4) and img.shape[0] not in (1, 3, 4):
        return img.transpose(2, 0, 1)
    return img


def to_tensor(img, data_format="CHW"):
    arr = _chw(img).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    return arr


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
    std = np.asarray(std, np.float32).reshape(-1, 1, 1)
    return (arr - mean) / std


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        c = arr.shape[0]
        mean = np.asarray(self.mean[:c] if len(self.mean) >= c else self.mean * c, np.float32).reshape(-1, 1, 1)
        std = np.asarray(self.std[:c] if len(self.std) >= c else self.std * c, np.float32).reshape(-1, 1, 1)
        return (arr - mean) / std


def resize(img, size, interpolation="bilinear"):
    arr = _chw(np.asarray(img)).astype(np.float32)
    c, h, w = arr.shape
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, :, None]
    wx = (xs - x0)[None, None, :]
    a = arr[:, y0][:, :, x0]
    b = arr[:, y0][:, :, x1]
    cta = arr[:, y1][:, :, x0]
    d = arr[:, y1][:, :, x1]
    return a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx + cta * wy * (1 - wx) + d * wy * wx


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def __call__(self, img):
        return resize(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        c, h, w = arr.shape
        th, tw = self.size
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[:, i : i + th, j : j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        c, h, w = arr.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[:, i : i + th, j : j + tw]


def hflip(img):
    return np.asarray(img)[..., ::-1].copy()


def vflip(img):
    arr = np.asarray(img)
    return arr[..., ::-1, :].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.fill = fill

    def __call__(self, img):
        arr = _chw(np.asarray(img))
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(arr, ((0, 0), (p[1], p[3]), (p[0], p[2])), constant_values=self.fill)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def __call__(self, img):
        import math

        arr = _chw(np.asarray(img)).astype(np.float32)
        angle = math.radians(pyrandom.uniform(*self.degrees))
        c, h, w = arr.shape
        cy, cx = (h - 1) / 2, (w - 1) / 2
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * math.cos(angle) - (xx - cx) * math.sin(angle)
        xs = cx + (yy - cy) * math.sin(angle) + (xx - cx) * math.cos(angle)
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        valid = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
        out = arr[:, yi, xi] * valid[None]
        return out


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        f = 1 + pyrandom.uniform(-self.value, self.value)
        return np.asarray(img, np.float32) * f


class ContrastTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        f = 1 + pyrandom.uniform(-self.value, self.value)
        mean = arr.mean()
        return (arr - mean) * f + mean
