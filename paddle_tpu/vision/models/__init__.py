"""Vision model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, BasicBlock, BottleneckBlock
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2
from .alexnet import AlexNet, alexnet

__all__ = [
    "LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "resnet152", "BasicBlock", "BottleneckBlock", "VGG", "vgg11", "vgg13",
    "vgg16", "vgg19", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
    "mobilenet_v2", "AlexNet", "alexnet",
]
