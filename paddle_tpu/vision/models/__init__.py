"""Vision model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet
from .resnet import (
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, BasicBlock,
    BottleneckBlock, wide_resnet50_2, wide_resnet101_2, resnext50_32x4d,
    resnext50_64x4d, resnext101_32x4d, resnext101_64x4d, resnext152_32x4d,
    resnext152_64x4d,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2
from .mobilenetv3 import (
    MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small, mobilenet_v3_large,
)
from .alexnet import AlexNet, alexnet
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .densenet import (
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264,
)
from .shufflenetv2 import (
    shufflenet_v2_swish,
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_33, shufflenet_v2_x0_5,
    shufflenet_v2_x1_0, shufflenet_v2_x1_5, shufflenet_v2_x2_0,
)
from .googlenet import GoogLeNet, googlenet
from .inceptionv3 import InceptionV3, inception_v3

__all__ = [
    "LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "resnet152", "BasicBlock", "BottleneckBlock", "VGG", "vgg11", "vgg13",
    "vgg16", "vgg19", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
    "mobilenet_v2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v3_small", "mobilenet_v3_large", "AlexNet", "alexnet",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
    "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d",
    "GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
]
