"""GoogLeNet / Inception v1 (reference:
python/paddle/vision/models/googlenet.py)."""
from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


class _ConvBN(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvBN(in_ch, c1, 1)
        self.b2 = nn.Sequential(_ConvBN(in_ch, c3r, 1), _ConvBN(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_ConvBN(in_ch, c5r, 1), _ConvBN(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _ConvBN(in_ch, proj, 1))

    def forward(self, x):
        from ... import concat

        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """Returns (main_out, aux1, aux2) in train mode like the reference;
    aux heads are identity-pooled classifiers."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _ConvBN(64, 64, 1),
            _ConvBN(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)
        # aux classifiers (train-mode extra outputs, reference contract)
        self.aux1 = nn.Sequential(nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                                  nn.Linear(512 * 16, num_classes))
        self.aux2 = nn.Sequential(nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                                  nn.Linear(528 * 16, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        a1 = self.aux1(x) if self.training else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        a2 = self.aux2(x) if self.training else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        out = self.fc(self.dropout(self.pool(x)).flatten(start_axis=1))
        if self.training:
            return out, a1, a2
        return out


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
