"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from ... import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


def _channel_shuffle(x, groups):
    import paddle_tpu.nn.functional as F

    return F.channel_shuffle(x, groups)


def _act_layer(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch // 2, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act),
            )
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act_layer(act),
            )

    def forward(self, x):
        from ... import chunk, concat

        if self.stride == 1:
            x1, x2 = chunk(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        chs = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), _act_layer(act),
        )
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = chs[0]
        for out_ch, repeats in zip(chs[1:4], (4, 8, 4)):
            stages.append(_InvertedResidual(in_ch, out_ch, 2, act))
            for _ in range(repeats - 1):
                stages.append(_InvertedResidual(out_ch, out_ch, 1, act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, chs[4], 1, bias_attr=False),
            nn.BatchNorm2D(chs[4]), _act_layer(act),
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(chs[4], num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(start_axis=1))
        return x


def _make(scale, name):
    def builder(pretrained=False, **kwargs):
        return ShuffleNetV2(scale=scale, **kwargs)

    builder.__name__ = name
    return builder


shufflenet_v2_x0_25 = _make(0.25, "shufflenet_v2_x0_25")
shufflenet_v2_x0_33 = _make(0.33, "shufflenet_v2_x0_33")
shufflenet_v2_x0_5 = _make(0.5, "shufflenet_v2_x0_5")
shufflenet_v2_x1_0 = _make(1.0, "shufflenet_v2_x1_0")
shufflenet_v2_x1_5 = _make(1.5, "shufflenet_v2_x1_5")
shufflenet_v2_x2_0 = _make(2.0, "shufflenet_v2_x2_0")


def shufflenet_v2_swish(pretrained=False, **kwargs):
    """ShuffleNetV2 with swish activation (reference
    shufflenet_v2_swish)."""
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)


__all__ += ["shufflenet_v2_swish"]
