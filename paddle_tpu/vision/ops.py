"""Vision ops (reference: python/paddle/vision/ops.py — roi_align, nms,
deform_conv2d, yolo_loss, box helpers; SURVEY §8.11).

TPU-native stance: the reference's hand-written CUDA kernels
(deformable_conv_op.cu, yolov3_loss_op) become vectorized gather/einsum
formulations that XLA fuses — bilinear sampling is four gathers and a
lerp, the im2col contraction is one einsum on the MXU."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer import Layer as _Layer

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "prior_box", "deform_conv2d", "yolo_loss", "DeformConv2D",
           "yolo_box", "generate_proposals", "distribute_fpn_proposals",
           "matrix_nms", "psroi_pool", "PSRoIPool", "RoIPool", "RoIAlign",
           "ConvNormActivation", "read_file", "decode_jpeg"]


def box_iou(boxes1, boxes2):
    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply(fn, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Host-side NMS (dynamic output shape — same as reference nms_op CPU)."""
    b = np.asarray(boxes._data)
    s = np.asarray(scores._data) if scores is not None else np.arange(len(b))[::-1].astype(np.float32)
    keep = _np_nms(b, s, iou_threshold)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_arr = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(feat):
        n, c, h, w = feat.shape
        offset = 0.5 if aligned else 0.0

        def one_roi(bi, box):
            x1, y1, x2, y2 = box * spatial_scale - offset
            bw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
            bh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
            ys = y1 + (jnp.arange(oh) + 0.5) * bh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * bw / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            img = feat[bi]
            out = (
                img[:, y0, x0] * (1 - wy) * (1 - wx)
                + img[:, y0, x1i] * (1 - wy) * wx
                + img[:, y1i, x0] * wy * (1 - wx)
                + img[:, y1i, x1i] * wy * wx
            )
            return out

        outs = [one_roi(int(batch_idx[i]), boxes_arr[i]) for i in range(boxes_arr.shape[0])]
        return jnp.stack(outs) if outs else jnp.zeros((0, c, oh, ow), feat.dtype)

    return apply(fn, x, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_arr = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(feat):
        n, c, h, w = feat.shape
        outs = []
        for i in range(boxes_arr.shape[0]):
            x1, y1, x2, y2 = np.round(boxes_arr[i] * spatial_scale).astype(int)
            x2, y2 = max(x2, x1 + 1), max(y2, y1 + 1)
            img = feat[int(batch_idx[i]), :, max(y1, 0):min(y2, h), max(x1, 0):min(x2, w)]
            # adaptive max pool to (oh, ow)
            hh, ww = img.shape[1], img.shape[2]
            rows = np.linspace(0, hh, oh + 1).astype(int)
            cols = np.linspace(0, ww, ow + 1).astype(int)
            pooled = jnp.stack([
                jnp.stack([
                    jnp.max(img[:, rows[r]:max(rows[r + 1], rows[r] + 1),
                                cols[s]:max(cols[s + 1], cols[s] + 1)], axis=(1, 2))
                    for s in range(ow)
                ], axis=-1)
                for r in range(oh)
            ], axis=-2)
            outs.append(pooled)
        return jnp.stack(outs) if outs else jnp.zeros((0, c, oh, ow), feat.dtype)

    return apply(fn, x, name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def fn(pb, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        var = (
            prior_box_var._data
            if isinstance(prior_box_var, Tensor)
            else jnp.asarray(prior_box_var if prior_box_var is not None else [1.0, 1.0, 1.0, 1.0])
        )
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tx - px) / pw, (ty - py) / ph,
                jnp.log(tw / pw), jnp.log(th / ph),
            ], axis=-1)
            return out / var.reshape(1, 4) if var.ndim <= 1 else out / var
        # decode
        dv = tb.reshape(tb.shape[0], -1, 4)
        v = var.reshape(1, 1, 4) if var.ndim <= 1 else var.reshape(var.shape[0], 1, 4)
        dv = dv * v
        ox = dv[..., 0] * pw[:, None] + px[:, None]
        oy = dv[..., 1] * ph[:, None] + py[:, None]
        ow_ = jnp.exp(dv[..., 2]) * pw[:, None]
        oh_ = jnp.exp(dv[..., 3]) * ph[:, None]
        return jnp.stack([ox - ow_ / 2, oy - oh_ / 2, ox + ow_ / 2, oy + oh_ / 2], axis=-1).squeeze(1)

    return apply(fn, prior_box, target_box, name="box_coder")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False, name=None):
    h, w = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] or ih / h
    step_w = steps[0] or iw / w
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for i in range(h):
        for j in range(w):
            cx = (j + offset) * step_w
            cy = (i + offset) * step_h
            for k, ms in enumerate(min_sizes):
                for a in ars:
                    bw = ms * np.sqrt(a) / 2
                    bh = ms / np.sqrt(a) / 2
                    boxes.append([(cx - bw) / iw, (cy - bh) / ih, (cx + bw) / iw, (cy + bh) / ih])
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k])
                    boxes.append([(cx - s / 2) / iw, (cy - s / 2) / ih, (cx + s / 2) / iw, (cy + s / 2) / ih])
    arr = np.asarray(boxes, np.float32).reshape(h, w, -1, 4)
    if clip:
        arr = np.clip(arr, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), arr.shape).copy()
    return Tensor(jnp.asarray(arr)), Tensor(jnp.asarray(var))


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference: vision/ops.py deform_conv2d
    over deformable_conv_op.cu). mask=None is v1; a [N, dg*Hf*Wf, Ho, Wo]
    mask modulates samples (v2).

    x:      [N, Cin, H, W]
    offset: [N, 2*dg*Hf*Wf, Ho, Wo] — per-tap (dy, dx) displacements
    weight: [Cout, Cin//groups, Hf, Wf]
    """
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    dg = int(deformable_groups)
    g = int(groups)

    def fn(xa, off, w, *rest):
        maybe_mask = rest[0] if (mask is not None) else None
        maybe_bias = rest[-1] if (bias is not None) else None
        N, Cin, H, W = xa.shape
        Cout, Cpg, Hf, Wf = w.shape
        K = Hf * Wf
        Ho = (H + 2 * ph - (dh * (Hf - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (Wf - 1) + 1)) // sw + 1

        # base sampling positions per output cell and kernel tap
        ho = jnp.arange(Ho)
        wo = jnp.arange(Wo)
        ki = jnp.arange(Hf)
        kj = jnp.arange(Wf)
        base_y = (ho[:, None] * sh - ph) + ki[None, :] * dh      # [Ho, Hf]
        base_x = (wo[:, None] * sw - pw) + kj[None, :] * dw      # [Wo, Wf]
        # -> [K, Ho, Wo]
        by = jnp.broadcast_to(
            base_y.T[:, None, :, None], (Hf, Wf, Ho, Wo)).reshape(K, Ho, Wo)
        bx = jnp.broadcast_to(
            base_x.T[None, :, None, :], (Hf, Wf, Ho, Wo)).reshape(K, Ho, Wo)

        off = off.reshape(N, dg, K, 2, Ho, Wo)
        sy = by[None, None] + off[:, :, :, 0]                    # [N,dg,K,Ho,Wo]
        sx = bx[None, None] + off[:, :, :, 1]

        # bilinear sample with zero padding outside the image
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = (sy - y0).astype(xa.dtype)
        wx = (sx - x0).astype(xa.dtype)
        xg = xa.reshape(N, dg, Cin // dg, H * W)

        def corner(yc, xc, wgt):
            inb = ((yc >= 0) & (yc <= H - 1) & (xc >= 0) & (xc <= W - 1))
            yi = jnp.clip(yc, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xc, 0, W - 1).astype(jnp.int32)
            flat = (yi * W + xi).reshape(N, dg, 1, -1)           # [N,dg,1,K*Ho*Wo]
            got = jnp.take_along_axis(
                xg, jnp.broadcast_to(flat, (N, dg, Cin // dg, flat.shape[-1])),
                axis=-1)
            got = got.reshape(N, dg, Cin // dg, K, Ho, Wo)
            w_ = (wgt * inb.astype(xa.dtype))[:, :, None]        # [N,dg,1,K,Ho,Wo]
            return got * w_

        sampled = (corner(y0, x0, (1 - wy) * (1 - wx))
                   + corner(y0, x0 + 1, (1 - wy) * wx)
                   + corner(y0 + 1, x0, wy * (1 - wx))
                   + corner(y0 + 1, x0 + 1, wy * wx))            # [N,dg,Cpd,K,Ho,Wo]
        if maybe_mask is not None:
            m = maybe_mask.reshape(N, dg, 1, K, Ho, Wo).astype(xa.dtype)
            sampled = sampled * m
        col = sampled.reshape(N, Cin, K, Ho, Wo)

        # grouped contraction: out[n,co,ho,wo] = sum_{ci,k} w * col
        colg = col.reshape(N, g, Cin // g, K, Ho, Wo)
        wg = w.reshape(g, Cout // g, Cpg, Hf * Wf)
        out = jnp.einsum("ngckhw,gock->ngohw", colg, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xa.dtype)
        if maybe_bias is not None:
            out = out + maybe_bias.reshape(1, Cout, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="deform_conv2d")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: vision/ops.py yolo_loss over
    yolov3_loss_op.h): sigmoid-CE on x/y/objectness/class, L1 on w/h,
    best-anchor target assignment, IoU>thresh ignore mask. Returns the
    per-sample loss [N].

    x:        [N, S*(5+class_num), H, W] head output for this scale
    gt_box:   [N, B, 4] (cx, cy, w, h) normalized to [0, 1]
    gt_label: [N, B] int class ids; zero-area boxes are padding
    anchors:  flat list [a0w, a0h, a1w, ...] in input-image pixels
    anchor_mask: indices of this scale's anchors within `anchors`
    """
    anchors_np = np.asarray(anchors, np.float32).reshape(-1, 2)
    amask = np.asarray(anchor_mask, np.int32)
    S = len(amask)
    C = int(class_num)
    # reference smoothing (yolov3_loss_op.h): delta = min(1/C, 1/40),
    # positive target 1-delta, negative target delta
    smooth = min(1.0 / max(C, 1), 1.0 / 40.0) if use_label_smooth else 0.0

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    def fn(xa, gb, gl, *maybe_score):
        N = xa.shape[0]
        H, W = xa.shape[2], xa.shape[3]
        in_h = H * downsample_ratio
        in_w = W * downsample_ratio
        p = xa.reshape(N, S, 5 + C, H, W)
        tx, ty, tw, th, tobj = p[:, :, 0], p[:, :, 1], p[:, :, 2], p[:, :, 3], p[:, :, 4]
        tcls = p[:, :, 5:]                                    # [N,S,C,H,W]
        B = gb.shape[1]
        if B == 0:
            # no ground truth at all: pure negative-objectness loss
            return jnp.sum(bce(tobj, jnp.zeros_like(tobj)), axis=(1, 2, 3))
        score = (maybe_score[0] if maybe_score
                 else jnp.ones((N, B), xa.dtype))

        valid = (gb[:, :, 2] > 0) & (gb[:, :, 3] > 0)         # [N,B]

        # -- target assignment: best IoU over ALL anchors, origin-aligned
        gw = gb[:, :, 2] * in_w                               # pixels
        gh = gb[:, :, 3] * in_h
        aw = anchors_np[:, 0][None, None]                     # [1,1,A]
        ah = anchors_np[:, 1][None, None]
        inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # [N,B]
        # position of the responsible cell
        gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)

        # per-scale slot of the best anchor (or -1)
        slot = jnp.full_like(best, -1)
        for s_idx, a_idx in enumerate(amask):
            slot = jnp.where(best == int(a_idx), s_idx, slot)
        take = valid & (slot >= 0)                            # [N,B]
        sl = jnp.clip(slot, 0, S - 1)

        # gather predictions at assigned cells: [N,B]
        def at_cells(t):                                      # t: [N,S,H,W]
            flat = t.reshape(N, S * H * W)
            idx = sl * (H * W) + gj * W + gi
            return jnp.take_along_axis(flat, idx, axis=1)

        box_scale = (2.0 - gb[:, :, 2] * gb[:, :, 3])         # small boxes up-weighted
        wgt = take.astype(xa.dtype) * score * box_scale

        # x/y: sigmoid CE against the sub-cell offset
        txy_lab_x = gb[:, :, 0] * W - gi.astype(xa.dtype)
        txy_lab_y = gb[:, :, 1] * H - gj.astype(xa.dtype)
        loss_xy = (bce(at_cells(tx), txy_lab_x) + bce(at_cells(ty), txy_lab_y)) * wgt

        # w/h: L1 on log-space targets
        aw_sel = jnp.asarray(anchors_np[:, 0])[amask][sl]
        ah_sel = jnp.asarray(anchors_np[:, 1])[amask][sl]
        tw_lab = jnp.log(jnp.maximum(gw / jnp.maximum(aw_sel, 1e-9), 1e-9))
        th_lab = jnp.log(jnp.maximum(gh / jnp.maximum(ah_sel, 1e-9), 1e-9))
        loss_wh = (jnp.abs(at_cells(tw) - tw_lab)
                   + jnp.abs(at_cells(th) - th_lab)) * wgt

        # objectness: positives at assigned cells; negatives elsewhere
        # unless the predicted box IoU with any gt exceeds ignore_thresh
        grid_x = jnp.arange(W, dtype=xa.dtype)[None, None, None, :]
        grid_y = jnp.arange(H, dtype=xa.dtype)[None, None, :, None]
        a_w = jnp.asarray(anchors_np[:, 0])[amask][None, :, None, None]
        a_h = jnp.asarray(anchors_np[:, 1])[amask][None, :, None, None]
        px = (jax.nn.sigmoid(tx) * scale_x_y - 0.5 * (scale_x_y - 1.0)
              + grid_x) / W
        py = (jax.nn.sigmoid(ty) * scale_x_y - 0.5 * (scale_x_y - 1.0)
              + grid_y) / H
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * a_w / in_w
        ph = jnp.exp(jnp.clip(th, -10, 10)) * a_h / in_h

        def pairwise_iou(bx, by, bw, bh):                     # vs all gts
            px1, px2 = bx - bw / 2, bx + bw / 2
            py1, py2 = by - bh / 2, by + bh / 2
            gx1 = (gb[:, :, 0] - gb[:, :, 2] / 2)[:, :, None, None, None]
            gx2 = (gb[:, :, 0] + gb[:, :, 2] / 2)[:, :, None, None, None]
            gy1 = (gb[:, :, 1] - gb[:, :, 3] / 2)[:, :, None, None, None]
            gy2 = (gb[:, :, 1] + gb[:, :, 3] / 2)[:, :, None, None, None]
            iw = jnp.maximum(
                jnp.minimum(px2[:, None], gx2) - jnp.maximum(px1[:, None], gx1), 0)
            ih = jnp.maximum(
                jnp.minimum(py2[:, None], gy2) - jnp.maximum(py1[:, None], gy1), 0)
            inter = iw * ih
            union = (bw * bh)[:, None] + (
                gb[:, :, 2] * gb[:, :, 3])[:, :, None, None, None] - inter
            return inter / jnp.maximum(union, 1e-9)           # [N,B,S,H,W]

        iou = pairwise_iou(px, py, pw, ph)
        iou = jnp.where(valid[:, :, None, None, None], iou, 0.0)
        ignore = (jnp.max(iou, axis=1) > ignore_thresh)       # [N,S,H,W]

        # reference semantics: positives target 1.0 with WEIGHT gt_score
        # (mixup), negatives target 0.0 unless IoU-ignored
        idx = sl * (H * W) + gj * W + gi
        score_map = _scatter_max(jnp.zeros((N, S * H * W), xa.dtype), idx,
                                 take.astype(xa.dtype) * score)
        score_map = score_map.reshape(N, S, H, W)
        pos = score_map > 0
        obj_target = pos.astype(xa.dtype)
        obj_w = jnp.where(pos, score_map, jnp.where(~ignore, 1.0, 0.0))
        loss_obj = bce(tobj, obj_target) * obj_w

        # classification at assigned cells
        cls_lab = jax.nn.one_hot(jnp.clip(gl, 0, C - 1), C, dtype=xa.dtype)
        cls_lab = cls_lab * (1.0 - 2.0 * smooth) + smooth  # pos 1-d, neg d
        flat_cls = tcls.transpose(0, 1, 3, 4, 2).reshape(N, S * H * W, C)
        pred_cls = jnp.take_along_axis(
            flat_cls, idx[..., None].astype(jnp.int32), axis=1)  # [N,B,C]
        loss_cls = jnp.sum(bce(pred_cls, cls_lab), -1) * take.astype(
            xa.dtype) * score

        per_n = (jnp.sum(loss_xy + loss_wh + loss_cls, axis=1)
                 + jnp.sum(loss_obj, axis=(1, 2, 3)))
        return per_n

    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(gt_score)
    return apply(fn, *args, name="yolo_loss")


def _scatter_max(flat, idx, val):
    """flat [N, M], idx/val [N, B] -> max-scatter (duplicate cells keep the
    strongest target)."""
    return jax.vmap(lambda f, i, v: f.at[i].max(v))(flat, idx, val)


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d (reference: vision/ops.py DeformConv2D).
    forward(x, offset, mask=None) -> feature map."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        from ..nn.initializer import Normal, Constant

        ks = _pair(kernel_size)
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        fan = in_channels * ks[0] * ks[1]
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr,
            default_initializer=Normal(std=(2.0 / fan) ** 0.5))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr,
                default_initializer=Constant(0.0))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._attrs)


# ---------------------------------------------------------------------------
# YOLO box decoding (reference: vision/ops.py yolo_box / yolo_box_op.h)
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode the raw YOLOv3 head [N, na*(5+class_num), H, W] into boxes and
    class scores (reference: python/paddle/vision/ops.py:261 yolo_box,
    yolo_box_op kernels). Pure jnp — one fused elementwise+gather program,
    no per-cell loops.

    Returns (boxes [N, H*W*na, 4] xyxy in image pixels, scores
    [N, H*W*na, class_num]); predictions whose objectness confidence is
    below `conf_thresh` are zeroed, matching the reference contract.
    """
    na = len(anchors) // 2

    def fn(xa, img):
        n, c, h, w = xa.shape
        if iou_aware:
            iou_pred = xa[:, :na]            # [N, na, H, W]
            xa = xa[:, na:]
        xa = xa.reshape(n, na, 5 + class_num, h, w)
        grid_x = jnp.arange(w, dtype=jnp.float32)[None, :]
        grid_y = jnp.arange(h, dtype=jnp.float32)[:, None]
        anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
        in_w = float(downsample_ratio * w)
        in_h = float(downsample_ratio * h)
        sig = jax.nn.sigmoid
        # centers: scale_x_y stretches the sigmoid around 0.5 (YOLOv4 trick)
        cx = (sig(xa[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_x) / w
        cy = (sig(xa[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_y) / h
        bw = jnp.exp(xa[:, :, 2]) * anc[None, :, 0, None, None] / in_w
        bh = jnp.exp(xa[:, :, 3]) * anc[None, :, 1, None, None] / in_h
        conf = sig(xa[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * \
                sig(iou_pred) ** iou_aware_factor
        cls = sig(xa[:, :, 5:]) * conf[:, :, None]          # [N,na,C,H,W]
        keep = conf >= conf_thresh
        img_h = img[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = img[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * img_w
        y1 = (cy - bh / 2) * img_h
        x2 = (cx + bw / 2) * img_w
        y2 = (cy + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
        scores = cls * keep[:, :, None]
        # [N, na, H, W, 4] -> [N, na*H*W, 4]; scores -> [N, na*H*W, class_num]
        boxes = boxes.reshape(n, na * h * w, 4)
        scores = jnp.moveaxis(scores, 2, -1).reshape(n, na * h * w, class_num)
        return boxes, scores

    return apply(fn, x, img_size, name="yolo_box")


# ---------------------------------------------------------------------------
# Proposal-stage ops (reference: vision/ops.py generate_proposals /
# distribute_fpn_proposals / matrix_nms — CUDA ops generate_proposals_v2_op,
# distribute_fpn_proposals_op, matrix_nms_op)
# ---------------------------------------------------------------------------

def _np_nms(boxes, scores, thresh, eta=1.0):
    """Greedy NMS core shared by nms() and generate_proposals(). eta < 1 is
    the reference's ADAPTIVE mode (locality_aware_nms_op.cc:229 /
    nms_util.h): after each kept box the threshold decays (thresh *= eta
    while > 0.5), so suppression gets progressively stricter within the
    pass — it never re-admits a suppressed box."""
    order = np.argsort(-scores)
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    keep, suppressed = [], np.zeros(len(boxes), bool)
    thresh = float(thresh)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(boxes[i, 0], boxes[:, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[:, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[:, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > thresh
        suppressed[i] = True
        if eta < 1.0 and thresh > 0.5:
            thresh *= eta
    return np.asarray(keep, np.int64)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference: vision/ops.py:2020
    generate_proposals → generate_proposals_v2 op). Host-side: the output
    roster is dynamically sized and NMS is order-sequential, exactly like
    the reference CPU/CUDA op's host-visible contract.

    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors/variances
    [H, W, A, 4] (or [H*W*A, 4]). Returns (rpn_rois [R,4], rpn_roi_probs
    [R,1]) plus rois_num per image when return_rois_num=True.
    """
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    dl = np.asarray(bbox_deltas._data if isinstance(bbox_deltas, Tensor)
                    else bbox_deltas)
    im = np.asarray(img_size._data if isinstance(img_size, Tensor) else img_size)
    an = np.asarray(anchors._data if isinstance(anchors, Tensor)
                    else anchors).reshape(-1, 4)
    va = np.asarray(variances._data if isinstance(variances, Tensor)
                    else variances).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, rois_num = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)            # [H*W*A]
        d = dl[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, anc, var = s[order], d[order], an[order], va[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        cx = var[:, 0] * d[:, 0] * aw + acx
        cy = var[:, 1] * d[:, 1] * ah + acy
        bw = np.exp(np.minimum(var[:, 2] * d[:, 2], 10.0)) * aw
        bh = np.exp(np.minimum(var[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], 1)
        ih, iw = im[i, 0], im[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        # FilterBoxes parity (bbox_util.h:199): min_size clamps to >= 1,
        # and with pixel_offset the box center must lie inside the image
        msz = max(min_size, 1.0)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        ok = (ws >= msz) & (hs >= msz)
        if pixel_offset:
            ok &= ((boxes[:, 0] + ws / 2 <= iw) &
                   (boxes[:, 1] + hs / 2 <= ih))
        boxes, s = boxes[ok], s[ok]
        keep = _np_nms(boxes, s, nms_thresh, eta=eta)[:post_nms_top_n]
        all_rois.append(boxes[keep])
        all_probs.append(s[keep, None])
        rois_num.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0), jnp.float32))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0), jnp.float32))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(rois_num, jnp.int32))
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (reference: vision/ops.py:1150,
    distribute_fpn_proposals_op). level = floor(refer_level +
    log2(sqrt(area) / refer_scale)), clipped to [min_level, max_level].

    Returns (multi_rois list low→high level, restore_ind [R,1]) and, when
    rois_num is given, the per-level per-image roi counts.
    """
    r = np.asarray(fpn_rois._data if isinstance(fpn_rois, Tensor) else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((r[:, 2] - r[:, 0] + off) *
                            (r[:, 3] - r[:, 1] + off), 0, None))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, order, nums_per_level = [], [], []
    if rois_num is not None:
        bn = np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                        else rois_num)
        img_of = np.repeat(np.arange(len(bn)), bn)
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        multi_rois.append(Tensor(jnp.asarray(r[idx], jnp.float32)))
        order.append(idx)
        if rois_num is not None:
            nums_per_level.append(Tensor(jnp.asarray(
                np.bincount(img_of[idx], minlength=len(bn)), jnp.int32)))
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    restore_ind = Tensor(jnp.asarray(restore[:, None], jnp.int32))
    if rois_num is not None:
        return multi_rois, restore_ind, nums_per_level
    return multi_rois, restore_ind


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: vision/ops.py:2187, matrix_nms_op — SOLOv2).
    Decay is computed from the full pairwise IoU matrix in one shot — the
    parallel-friendly NMS variant (no sequential suppression), matching the
    reference kernel's min-over-higher-scored formulation.

    bboxes [N, M, 4], scores [N, C, M]. Returns Out [R, 6]
    (label, score, x1, y1, x2, y2) + optional index and per-image counts.
    """
    bb = np.asarray(bboxes._data if isinstance(bboxes, Tensor) else bboxes)
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores)
    n, c, m = sc.shape
    outs, inds, nums = [], [], []
    for i in range(n):
        per_img = []
        for cls in range(c):
            if cls == background_label:
                continue
            s = sc[i, cls]
            sel = np.nonzero(s > score_threshold)[0]
            if len(sel) == 0:
                continue
            order = sel[np.argsort(-s[sel])][:nms_top_k]
            b, s2 = bb[i][order], s[order]
            noff = 0.0 if normalized else 1.0     # reference: +1 when pixel coords
            area = (b[:, 2] - b[:, 0] + noff) * (b[:, 3] - b[:, 1] + noff)
            lt = np.maximum(b[:, None, :2], b[None, :, :2])
            rb = np.minimum(b[:, None, 2:], b[None, :, 2:])
            wh = np.clip(rb - lt + noff, 0, None)
            inter = wh[..., 0] * wh[..., 1]
            iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)
            iou = np.triu(iou, 1)                  # iou[j, k], j higher-scored
            comp = iou.max(0)                      # comp[j]: j's own worst overlap
            # decay[j, k] = f(iou_jk) / f(comp_j): how much suppressor j
            # (discounted by its own compensation) decays k (SOLOv2 eq. 4)
            if use_gaussian:
                # reference oracle: exp((comp^2 - iou^2) * sigma)
                decay = np.exp((comp[:, None] ** 2 - iou ** 2) * gaussian_sigma)
            else:
                decay = (1 - iou) / (1 - comp[:, None] + 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), 1) > 0, decay, np.inf)
            decay = decay.min(0)
            decay = np.where(np.isinf(decay), 1.0, decay)
            s3 = s2 * decay
            ok = s3 > post_threshold
            for j in np.nonzero(ok)[0]:
                per_img.append((cls, s3[j], *b[j], order[j] + i * m))
        per_img.sort(key=lambda t: -t[1])
        if keep_top_k > 0:
            per_img = per_img[:keep_top_k]
        nums.append(len(per_img))
        for t in per_img:
            outs.append(t[:6])
            inds.append(t[6])
    out = Tensor(jnp.asarray(np.asarray(outs, np.float32).reshape(-1, 6)))
    # reference contract (vision/ops.py:2332): ALWAYS (out, rois_num, index)
    # with None placeholders for the outputs not requested
    rois_num = (Tensor(jnp.asarray(nums, jnp.int32))
                if return_rois_num else None)
    index = (Tensor(jnp.asarray(np.asarray(inds, np.int64)[:, None]))
             if return_index else None)
    return out, rois_num, index


# ---------------------------------------------------------------------------
# Position-sensitive RoI pooling + layer wrappers + image IO
# (reference: vision/ops.py psroi_pool:1383, RoIPool:1578, RoIAlign:1745,
#  ConvNormActivation:1793, read_file:1288, decode_jpeg:1333)
# ---------------------------------------------------------------------------

def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pooling (R-FCN; reference
    psroi_pool_op). Input channels C must equal out_c * oh * ow; output
    channel (co, i, j) averages input channel co*oh*ow + i*ow + j over the
    (i, j) bin of each RoI.

    TPU-native formulation: a 2-D summed-area table (cumsum twice) turns
    every bin average into 4 gathers — no dynamic-extent slicing, static
    shapes [R, out_c, oh, ow] for XLA.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor)
                    else boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(xa, bx):
        n, c, hh, ww = xa.shape
        out_c = c // (oh * ow)
        assert out_c * oh * ow == c, (
            f"psroi_pool needs channels divisible by {oh}*{ow}, got {c}")
        # summed-area table with a leading zero row/col: sat[., y, x] =
        # sum of xa[., :y, :x]
        sat = jnp.cumsum(jnp.cumsum(xa, axis=2), axis=3)
        sat = jnp.pad(sat, ((0, 0), (0, 0), (1, 0), (1, 0)))

        def one_roi(b, img_i):
            x1, y1, x2, y2 = b * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bw, bh = rw / ow, rh / oh
            ii = jnp.arange(oh, dtype=jnp.float32)
            jj = jnp.arange(ow, dtype=jnp.float32)
            hs = jnp.clip(jnp.floor(y1 + ii * bh), 0, hh).astype(jnp.int32)
            he = jnp.clip(jnp.ceil(y1 + (ii + 1) * bh), 0, hh).astype(jnp.int32)
            ws = jnp.clip(jnp.floor(x1 + jj * bw), 0, ww).astype(jnp.int32)
            we = jnp.clip(jnp.ceil(x1 + (jj + 1) * bw), 0, ww).astype(jnp.int32)
            feat = sat[img_i]                       # [C, H+1, W+1]
            # position-sensitive channel for (co, i, j)
            co = jnp.arange(out_c)[:, None, None]
            ci = (co * oh * ow + ii.astype(jnp.int32)[None, :, None] * ow
                  + jj.astype(jnp.int32)[None, None, :])   # [out_c, oh, ow]
            hs_, he_ = hs[None, :, None], he[None, :, None]
            ws_, we_ = ws[None, None, :], we[None, None, :]
            ssum = (feat[ci, he_, we_] - feat[ci, hs_, we_]
                    - feat[ci, he_, ws_] + feat[ci, hs_, ws_])
            cnt = jnp.maximum((he_ - hs_) * (we_ - ws_), 1)
            empty = (he_ <= hs_) | (we_ <= ws_)
            return jnp.where(empty, 0.0, ssum / cnt)

        return jax.vmap(one_roi)(bx, batch_idx)

    return apply(fn, x, boxes, name="psroi_pool")


class PSRoIPool(_Layer):
    """Layer form of psroi_pool (reference vision/ops.py:1456)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class RoIPool(_Layer):
    """Layer form of roi_pool (reference vision/ops.py:1578)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class RoIAlign(_Layer):
    """Layer form of roi_align (reference vision/ops.py:1745)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


from ..nn import BatchNorm2D as _BatchNorm2D, Conv2D as _Conv2D, \
    ReLU as _ReLU, Sequential as _Sequential  # noqa: E402


class ConvNormActivation(_Sequential):
    """Conv2D + norm + activation block (reference vision/ops.py:1793;
    torchvision-style). norm_layer/activation_layer are classes, not
    instances; None skips the slot."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=_BatchNorm2D,
                 activation_layer=_ReLU, dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [_Conv2D(in_channels, out_channels, kernel_size, stride,
                          padding, dilation=dilation, groups=groups,
                          bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def read_file(filename, name=None):
    """Read a file's bytes as a 1-D uint8 Tensor (reference
    vision/ops.py:1288 read_file — host-side IO, no device involvement)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes (1-D uint8 Tensor) to a CHW uint8 image tensor
    (reference vision/ops.py:1333 decode_jpeg — host-side; the reference
    uses nvjpeg on GPU, here PIL decodes on host and the array moves to
    device like any other input)."""
    import io as _io

    from PIL import Image

    raw = bytes(np.asarray(x._data if isinstance(x, Tensor) else x,
                           np.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None]                       # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)          # [C, H, W]
    return Tensor(jnp.asarray(arr))
