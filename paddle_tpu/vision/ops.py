"""Vision ops (reference: python/paddle/vision/ops.py — roi_align, nms,
deform_conv, yolo helpers; SURVEY §8.11). Round-1 scope: the geometry ops
used by detection heads; specialized CUDA kernels (deform_conv) land later."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder", "prior_box"]


def box_iou(boxes1, boxes2):
    def fn(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply(fn, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Host-side NMS (dynamic output shape — same as reference nms_op CPU)."""
    b = np.asarray(boxes._data)
    s = np.asarray(scores._data) if scores is not None else np.arange(len(b))[::-1].astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for _i in order:
        if suppressed[_i]:
            continue
        keep.append(_i)
        xx1 = np.maximum(b[_i, 0], b[:, 0])
        yy1 = np.maximum(b[_i, 1], b[:, 1])
        xx2 = np.minimum(b[_i, 2], b[:, 2])
        yy2 = np.minimum(b[_i, 3], b[:, 3])
        w = np.clip(xx2 - xx1, 0, None)
        h = np.clip(yy2 - yy1, 0, None)
        inter = w * h
        iou = inter / (areas[_i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[_i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_arr = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(feat):
        n, c, h, w = feat.shape
        offset = 0.5 if aligned else 0.0

        def one_roi(bi, box):
            x1, y1, x2, y2 = box * spatial_scale - offset
            bw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
            bh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
            ys = y1 + (jnp.arange(oh) + 0.5) * bh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * bw / ow
            yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            img = feat[bi]
            out = (
                img[:, y0, x0] * (1 - wy) * (1 - wx)
                + img[:, y0, x1i] * (1 - wy) * wx
                + img[:, y1i, x0] * wy * (1 - wx)
                + img[:, y1i, x1i] * wy * wx
            )
            return out

        outs = [one_roi(int(batch_idx[i]), boxes_arr[i]) for i in range(boxes_arr.shape[0])]
        return jnp.stack(outs) if outs else jnp.zeros((0, c, oh, ow), feat.dtype)

    return apply(fn, x, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    boxes_arr = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    bn = np.asarray(boxes_num._data if isinstance(boxes_num, Tensor) else boxes_num)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(feat):
        n, c, h, w = feat.shape
        outs = []
        for i in range(boxes_arr.shape[0]):
            x1, y1, x2, y2 = np.round(boxes_arr[i] * spatial_scale).astype(int)
            x2, y2 = max(x2, x1 + 1), max(y2, y1 + 1)
            img = feat[int(batch_idx[i]), :, max(y1, 0):min(y2, h), max(x1, 0):min(x2, w)]
            # adaptive max pool to (oh, ow)
            hh, ww = img.shape[1], img.shape[2]
            rows = np.linspace(0, hh, oh + 1).astype(int)
            cols = np.linspace(0, ww, ow + 1).astype(int)
            pooled = jnp.stack([
                jnp.stack([
                    jnp.max(img[:, rows[r]:max(rows[r + 1], rows[r] + 1),
                                cols[s]:max(cols[s + 1], cols[s] + 1)], axis=(1, 2))
                    for s in range(ow)
                ], axis=-1)
                for r in range(oh)
            ], axis=-2)
            outs.append(pooled)
        return jnp.stack(outs) if outs else jnp.zeros((0, c, oh, ow), feat.dtype)

    return apply(fn, x, name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def fn(pb, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        var = (
            prior_box_var._data
            if isinstance(prior_box_var, Tensor)
            else jnp.asarray(prior_box_var if prior_box_var is not None else [1.0, 1.0, 1.0, 1.0])
        )
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tx - px) / pw, (ty - py) / ph,
                jnp.log(tw / pw), jnp.log(th / ph),
            ], axis=-1)
            return out / var.reshape(1, 4) if var.ndim <= 1 else out / var
        # decode
        dv = tb.reshape(tb.shape[0], -1, 4)
        v = var.reshape(1, 1, 4) if var.ndim <= 1 else var.reshape(var.shape[0], 1, 4)
        dv = dv * v
        ox = dv[..., 0] * pw[:, None] + px[:, None]
        oy = dv[..., 1] * ph[:, None] + py[:, None]
        ow_ = jnp.exp(dv[..., 2]) * pw[:, None]
        oh_ = jnp.exp(dv[..., 3]) * ph[:, None]
        return jnp.stack([ox - ow_ / 2, oy - oh_ / 2, ox + ow_ / 2, oy + oh_ / 2], axis=-1).squeeze(1)

    return apply(fn, prior_box, target_box, name="box_coder")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False, name=None):
    h, w = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] or ih / h
    step_w = steps[0] or iw / w
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for i in range(h):
        for j in range(w):
            cx = (j + offset) * step_w
            cy = (i + offset) * step_h
            for k, ms in enumerate(min_sizes):
                for a in ars:
                    bw = ms * np.sqrt(a) / 2
                    bh = ms / np.sqrt(a) / 2
                    boxes.append([(cx - bw) / iw, (cy - bh) / ih, (cx + bw) / iw, (cy + bh) / ih])
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k])
                    boxes.append([(cx - s / 2) / iw, (cy - s / 2) / ih, (cx + s / 2) / iw, (cy + s / 2) / ih])
    arr = np.asarray(boxes, np.float32).reshape(h, w, -1, 4)
    if clip:
        arr = np.clip(arr, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), arr.shape).copy()
    return Tensor(jnp.asarray(arr)), Tensor(jnp.asarray(var))
