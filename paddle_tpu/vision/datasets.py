"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: datasets load from local files when present
(standard IDX/cifar pickle formats under ~/.cache/paddle_tpu/ or an explicit
path); otherwise they fall back to a deterministic synthetic sample with the
right shapes/label space (clearly flagged via `.synthetic`) so examples and
tests run anywhere.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder", "ImageFolder"]

_CACHE = os.path.expanduser(os.environ.get("PTPU_DATA_HOME", "~/.cache/paddle_tpu"))


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    images = (rng.rand(n, *shape) * 80).astype(np.uint8)
    labels = rng.randint(0, num_classes, size=(n,)).astype(np.int64)
    # strongly separable classes (a bright band at a class-specific row) so
    # tiny models can overfit quickly in tests
    h = shape[0]
    band = max(h // num_classes, 1)
    for i in range(n):
        c = int(labels[i])
        r0 = (c * band) % (h - band + 1)
        images[i, r0 : r0 + band, ...] = 230
    return images, labels


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2", size=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = False
        n_default = 60000 if mode == "train" else 10000
        images = labels = None
        img_p = image_path or os.path.join(
            _CACHE, "mnist", f"{'train' if mode == 'train' else 't10k'}-images-idx3-ubyte.gz"
        )
        lbl_p = label_path or os.path.join(
            _CACHE, "mnist", f"{'train' if mode == 'train' else 't10k'}-labels-idx1-ubyte.gz"
        )
        if os.path.exists(img_p) and os.path.exists(lbl_p):
            images = self._read_idx_images(img_p)
            labels = self._read_idx_labels(lbl_p)
        else:
            self.synthetic = True
            n = size or min(n_default, 2048)
            images, labels = _synthetic_images(n, (28, 28), 10, seed=42 if mode == "train" else 7)
        self.images = images
        self.labels = labels

    @staticmethod
    def _read_idx_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    @staticmethod
    def _read_idx_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.array([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2", size=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = True
        n = size or 1024
        self.images, self.labels = _synthetic_images(
            n, (32, 32, 3), self.NUM_CLASSES, seed=13 if mode == "train" else 17
        )
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile

            with tarfile.open(data_file) as tf:
                imgs, lbls = [], []
                names = (
                    [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" else ["test_batch"]
                )
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in names:
                        d = pickle.load(tf.extractfile(m), encoding="bytes")
                        imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                        lbls.extend(d.get(b"labels", d.get(b"fine_labels")))
                if imgs:
                    self.images = np.concatenate(imgs)
                    self.labels = np.asarray(lbls, np.int64)
                    self.synthetic = False

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32).transpose(2, 0, 1) / 255.0
        label = np.array([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """Image-folder dataset (reference: paddle.vision.DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        exts = extensions or (".npy",)
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        exts = extensions or (".npy",)
        self.samples = [
            os.path.join(root, f)
            for f in sorted(os.listdir(root))
            if f.lower().endswith(exts)
        ]
        self.loader = loader or (lambda p: np.load(p))

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference vision/datasets/flowers.py): (image CHW,
    label) pairs; synthetic fallback with the 102-class label space."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = True
        n = 256 if mode == "train" else 64
        # HWC like Cifar: _synthetic_images writes its class-separable
        # band across shape[0] (rows)
        self.images, self.labels = _synthetic_images(
            n, (32, 32, 3), 102, seed=11 if mode == "train" else 13)

    def __getitem__(self, idx):
        img, label = self.images[idx], int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference vision/datasets/voc2012.py):
    (image CHW, mask HW) pairs; synthetic fallback draws blocky class
    regions so segmentation losses have real structure."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.synthetic = True
        n = 64 if mode == "train" else 16
        rng = np.random.RandomState(17 if mode == "train" else 19)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        masks = np.zeros((n, 32, 32), np.int64)
        for i in range(n):
            for _ in range(3):
                cls = rng.randint(1, self.NUM_CLASSES)
                y0, x0 = rng.randint(0, 24, 2)
                masks[i, y0:y0 + 8, x0:x0 + 8] = cls
        self.masks = masks

    def __getitem__(self, idx):
        img, mask = self.images[idx], self.masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.images)


__all__ += ["Flowers", "VOC2012"]
