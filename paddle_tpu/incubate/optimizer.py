"""incubate.optimizer (reference: python/paddle/incubate/optimizer/
lbfgs.py — closure-driven L-BFGS with optional strong-Wolfe line search).

TPU-native notes: the two-loop recursion is a handful of dot products on
one flattened parameter vector — pure jnp, negligible next to the
closure's forward/backward, so no custom kernel is warranted. The
closure re-runs the whole model; with jit.compile-wrapped closures each
line-search probe is one XLA executable call.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["LBFGS"]


class LBFGS:
    """L-BFGS (history-based quasi-Newton). step(closure) semantics match
    the reference: `closure` clears grads, computes the loss, calls
    backward, and returns the loss tensor."""

    def __init__(self, learning_rate: float = 1.0, max_iter: int = 20,
                 max_eval: Optional[int] = None, tolerance_grad: float = 1e-7,
                 tolerance_change: float = 1e-9, history_size: int = 100,
                 line_search_fn: Optional[str] = None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        if parameters is None:
            raise ValueError("LBFGS requires an explicit parameter list")
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self._params: List[Tensor] = list(parameters)
        self.lr = float(learning_rate)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._wd = float(weight_decay) if weight_decay else 0.0
        self._grad_clip = grad_clip
        self._s, self._y, self._rho = [], [], []
        self._n_evals = 0

    # -- flat helpers ------------------------------------------------------
    def _gather_flat_grad(self):
        grads = [(p.grad._data if p.grad is not None
                  else jnp.zeros(p.shape, p.dtype)) for p in self._params]
        if self._grad_clip is not None:
            grads = self._grad_clip.apply(grads)
        if self._wd:
            grads = [g + self._wd * p._data
                     for g, p in zip(grads, self._params)]
        return jnp.concatenate([g.reshape(-1) for g in grads])

    def _gather_flat_params(self):
        return jnp.concatenate([p._data.reshape(-1) for p in self._params])

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params:
            n = int(jnp.size(p._data))
            p._set_data(flat[off:off + n].reshape(p.shape).astype(p.dtype))
            off += n

    def clear_grad(self):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- direction ---------------------------------------------------------
    def _two_loop(self, grad):
        q = grad
        alphas = []
        for s, y, rho in zip(reversed(self._s), reversed(self._y),
                             reversed(self._rho)):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._s:
            gamma = jnp.dot(self._s[-1], self._y[-1]) / jnp.maximum(
                jnp.dot(self._y[-1], self._y[-1]), 1e-10)
            q = q * gamma
        for (s, y, rho), a in zip(zip(self._s, self._y, self._rho),
                                  reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + s * (a - b)
        return -q

    # -- line search -------------------------------------------------------
    def _eval(self, closure, flat_x):
        self._set_flat_params(flat_x)
        loss = closure()
        self._n_evals += 1
        return float(loss), self._gather_flat_grad()

    def _budget_left(self):
        return self._n_evals < self.max_eval

    def _strong_wolfe(self, closure, x0, d, f0, g0, t0,
                      c1=1e-4, c2=0.9, max_ls=25):
        """Bracket + bisection-zoom strong-Wolfe search along d from x0.
        Returns (t, f_t, grad_at_t) with params LEFT AT x0 + t*d, so the
        caller never re-evaluates. Honors the global max_eval budget."""
        gtd0 = float(jnp.dot(g0, d))
        t_prev, f_prev, g_prev = 0.0, f0, gtd0
        t = t0
        f_t, g_flat = self._eval(closure, x0 + t * d)
        bracket = None
        for _ in range(max_ls):
            gtd = float(jnp.dot(g_flat, d))
            if f_t > f0 + c1 * t * gtd0 or f_t >= f_prev:
                bracket = (t_prev, f_prev, t)
                break
            if abs(gtd) <= -c2 * gtd0 or not self._budget_left():
                return t, f_t, g_flat
            if gtd >= 0:
                bracket = (t_prev, f_prev, t)
                break
            t_prev, f_prev, g_prev = t, f_t, gtd
            t = t * 2.0
            f_t, g_flat = self._eval(closure, x0 + t * d)
        if bracket is None:
            return t, f_t, g_flat
        lo_t, lo_f, hi_t = bracket
        best = (t, f_t, g_flat)
        for _ in range(max_ls):
            if not self._budget_left():
                break
            t = 0.5 * (lo_t + hi_t)   # bisection zoom (robust)
            f_t, g_flat = self._eval(closure, x0 + t * d)
            gtd = float(jnp.dot(g_flat, d))
            if f_t <= best[1]:
                best = (t, f_t, g_flat)
            if f_t > f0 + c1 * t * gtd0 or f_t >= lo_f:
                hi_t = t
            else:
                if abs(gtd) <= -c2 * gtd0:
                    return t, f_t, g_flat
                lo_t, lo_f = t, f_t
            if abs(hi_t - lo_t) < 1e-10:
                break
        t, f_t, g_flat = best
        self._set_flat_params(x0 + t * d)   # leave params at the winner
        return t, f_t, g_flat

    # -- main --------------------------------------------------------------
    def step(self, closure: Callable):
        loss = closure()
        self._n_evals = 1
        f = float(loss)
        flat_grad = self._gather_flat_grad()
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tol_grad:
            return loss

        for _ in range(self.max_iter):
            d = self._two_loop(flat_grad)
            if not self._s:
                d = d / jnp.maximum(jnp.sum(jnp.abs(flat_grad)), 1.0)
            x0 = self._gather_flat_params()
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self.tol_change:
                break

            if self.line_search_fn == "strong_wolfe":
                t, new_f, new_grad = self._strong_wolfe(
                    closure, x0, d, f, flat_grad, t0=self.lr)
            else:
                t = self.lr
                new_f, new_grad = self._eval(closure, x0 + t * d)

            s = t * d
            y = new_grad - flat_grad
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                if len(self._s) >= self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
                    self._rho.pop(0)
                self._s.append(s)
                self._y.append(y)
                self._rho.append(1.0 / ys)

            delta = abs(new_f - f)
            f, flat_grad = new_f, new_grad
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tol_grad:
                break
            if delta < self.tol_change:
                break
            if self._n_evals >= self.max_eval:
                break
        return Tensor(jnp.asarray(f, jnp.float32))

    def state_dict(self):
        return {"s": [Tensor(a) for a in self._s],
                "y": [Tensor(a) for a in self._y],
                "rho": list(self._rho)}

    def set_state_dict(self, state):
        self._s = [t._data for t in state.get("s", [])]
        self._y = [t._data for t in state.get("y", [])]
        self._rho = list(state.get("rho", []))


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate/optimizer/lookahead.py
    LookAhead): every k steps the SLOW weights move alpha of the way toward
    the fast (inner-optimizer) weights, and the fast weights reset to the
    slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step = 0
        self._slow = {}

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        import jax.numpy as jnp

        if self._step == 0:
            # anchor the slow weights at the INITIAL params (reference
            # lookahead.py step-0 init) — lazily creating them at the
            # first sync would make that sync a no-op
            for p in self._parameter_list:
                if p.trainable:
                    self._slow[id(p)] = jnp.array(p._data, copy=True)
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k:
            return
        for p in self._parameter_list:
            if not p.trainable:
                continue
            slow = self._slow.get(id(p))
            if slow is None:      # param added after construction
                slow = jnp.array(p._data, copy=True)
            slow = slow + self.alpha * (p._data - slow)
            self._slow[id(p)] = slow
            # the param buffer must NOT alias the stored slow copy: the
            # inner optimizer's fused update donates its param inputs, and
            # astype on a same-dtype array returns the SAME buffer
            p._set_data(jnp.array(slow, copy=True).astype(p._data.dtype))

    def clear_grad(self, *a, **k):
        return self.inner_optimizer.clear_grad(*a, **k)

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def set_state_dict(self, state):
        return self.inner_optimizer.set_state_dict(state)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Running average of parameters for evaluation (reference
    incubate/optimizer/modelaverage.py): accumulates sums of param values;
    apply() swaps the averages in, restore() swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._sum = {}
        self._cnt = {}
        self._backup = {}

    def step(self):
        import jax.numpy as jnp

        for p in self._params:
            self._sum[id(p)] = self._sum.get(id(p), 0) + p._data.astype(
                jnp.float32)
            self._cnt[id(p)] = self._cnt.get(id(p), 0) + 1

    def apply(self, executor=None, need_restore=True):
        ma = self

        class _Ctx:
            def __enter__(self):
                for p in ma._params:
                    if ma._cnt.get(id(p)):
                        ma._backup[id(p)] = p._data
                        avg = ma._sum[id(p)] / ma._cnt[id(p)]
                        p._set_data(avg.astype(p._data.dtype))
                return self

            def __exit__(self, *exc):
                if need_restore:
                    ma.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._set_data(self._backup.pop(id(p)))
