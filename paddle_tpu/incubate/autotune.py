"""paddle.incubate.autotune parity (reference:
python/paddle/incubate/autotune.py — `set_config` switching kernel /
layout / dataloader tuning).

The real machinery lives in paddle_tpu.ops.autotune (Pallas block-geometry
sweeps, the TPU analog of the reference's cuDNN-algo search); this module
is the user-facing configuration surface at the reference's import path.
"""
from ..ops.autotune import AutoTuneCache, autotune, cache, set_config

__all__ = ["set_config"]
