"""paddle_tpu.incubate (reference: python/paddle/incubate/ — fused layers,
MoE, autograd functional; populated across rounds)."""
from . import nn
from . import autograd
from . import asp
from . import optimizer

__all__ = ["nn", "autograd", "asp", "optimizer"]
