"""paddle_tpu.incubate (reference: python/paddle/incubate/ — fused layers,
MoE, autograd functional; populated across rounds)."""
from . import nn
from . import distributed
from . import autograd
from . import asp
from . import autotune
from . import optimizer

__all__ = ["nn", "autograd", "asp", "autotune", "multiprocessing", "optimizer", "distributed"]


def __getattr__(name):
    # incubate.multiprocessing loads LAZILY: importing it registers
    # Tensor ForkingPickler reductions (a process-global side effect the
    # reference also gates behind an explicit `import
    # paddle.incubate.multiprocessing`), so a plain `import paddle_tpu`
    # must not install them.
    if name == "multiprocessing":
        import importlib

        mod = importlib.import_module(__name__ + ".multiprocessing")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# graph ops (reference incubate.graph_* — earlier homes of what became
# paddle.geometric; SURVEY §8.11) re-exported over the geometric kernels
from ..geometric import (  # noqa: E402
    segment_sum, segment_mean, segment_max, segment_min,
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
)
from ..geometric import send_u_recv as _send_u_recv  # noqa: E402


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """incubate.graph_send_recv (became geometric.send_u_recv)."""
    return _send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                        out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling over CSC (row, colptr) (reference
    incubate/operators/graph_khop_sampler.py:173). Host-side (the
    reference CPU kernel's contract; sampling is data-dependent).

    Returns (edge_src, edge_dst, sample_index, reindex_x) — edges in
    LOCAL (reindexed) ids, sample_index the unique node set (input nodes
    first), reindex_x the inputs' local ids — plus edge_eids when
    return_eids=True (requires sorted_eids, as in the reference)."""
    import numpy as np
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..core import random as _rng

    if return_eids and sorted_eids is None:
        raise ValueError("return_eids=True requires sorted_eids "
                         "(reference contract)")

    def _np(v):
        return np.asarray(v._data if isinstance(v, Tensor) else v)

    row_np, col_np = _np(row), _np(colptr)
    eid_np = _np(sorted_eids) if sorted_eids is not None else None
    x_np = _np(input_nodes).reshape(-1)
    seed = int(np.asarray(_rng.next_key())[-1]) % (2 ** 31)
    rng = np.random.RandomState(seed)

    seen = dict.fromkeys(x_np.tolist())
    frontier = x_np
    srcs, dsts, eids = [], [], []
    for size in sample_sizes:
        hop_new = dict()
        for n in frontier.tolist():
            lo, hi = int(col_np[n]), int(col_np[n + 1])
            pos = np.arange(lo, hi)
            if 0 <= size < len(pos):
                pos = rng.choice(pos, size=size, replace=False)
            nb = row_np[pos]
            srcs.append(nb)
            dsts.append(np.full(len(pos), n, row_np.dtype))
            if eid_np is not None:
                eids.append(eid_np[pos])
            for v in nb.tolist():
                if v not in seen:
                    hop_new[v] = None
        seen.update(hop_new)
        frontier = np.fromiter(hop_new.keys(), row_np.dtype)             if hop_new else np.zeros(0, row_np.dtype)
        if not len(frontier):
            break
    sample_index = np.fromiter(seen.keys(), np.int64)
    remap = {int(v): i for i, v in enumerate(sample_index)}
    cat = (lambda parts: np.concatenate(parts) if parts
           else np.zeros(0, np.int64))
    edge_src = np.asarray([remap[int(v)] for v in cat(srcs)], np.int64)
    edge_dst = np.asarray([remap[int(v)] for v in cat(dsts)], np.int64)
    reindex_x = np.asarray([remap[int(v)] for v in x_np], np.int64)
    out = (Tensor(jnp.asarray(edge_src)), Tensor(jnp.asarray(edge_dst)),
           Tensor(jnp.asarray(sample_index)), Tensor(jnp.asarray(reindex_x)))
    if return_eids:
        out = out + (Tensor(jnp.asarray(cat(eids))),)
    return out


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss (reference incubate.identity_loss — IPU
    pipeline marker; here it is the stated reduction). Integer codes per
    the reference: 0=sum, 1=mean, 2=none."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 0):
        return x.sum()
    if reduction in ("mean", 1):
        return x.mean()
    raise ValueError(f"unknown reduction {reduction!r}")


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (reference incubate.softmax_mask_fuse /
    fused_softmax_mask_op.cu): one jnp expression XLA fuses — the mask is
    never broadcast-materialized."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply

    return apply(lambda a, m: jax.nn.softmax(a + m.astype(a.dtype), axis=-1),
                 x, mask, name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the causal (upper-triangle masked) pattern fused
    (reference fused_softmax_mask_upper_triangle_op.cu)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def fn(a):
        sq, sk = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool))
        return jax.nn.softmax(jnp.where(causal, a, -1e30), axis=-1)

    return apply(fn, x, name="softmax_mask_fuse_upper_triangle")


from .optimizer import LookAhead, ModelAverage  # noqa: E402

__all__ += [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_reindex", "graph_sample_neighbors", "graph_send_recv",
    "graph_khop_sampler", "identity_loss", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "LookAhead", "ModelAverage",
]
