"""Automatic SParsity — 2:4 structured sparsity (reference:
python/paddle/incubate/asp/asp.py — prune_model:303, decorate:217,
create_mask / check_sparsity in utils.py:516; ASPOptimizer wraps step to
re-apply masks).

TPU note: XLA has no sparse-tensor-core path, so 2:4 here preserves the
*capability semantics* (mask creation, pruned training, mask persistence
through optimizer steps); dense masked matmuls still use the MXU."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = [
    "calculate_density", "create_mask", "check_sparsity", "prune_model",
    "decorate", "reset_excluded_layers", "set_excluded_layers",
]

_excluded_layers = set()
_masks = {}  # param name -> jnp mask


def calculate_density(x) -> float:
    arr = np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d_nm(flat, n, m):
    """Keep the n largest-|.| of every m consecutive values."""
    pad = (-len(flat)) % m
    v = np.abs(np.concatenate([flat, np.zeros(pad, flat.dtype)]))
    groups = v.reshape(-1, m)
    idx = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(-1)[: len(flat)]


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask along the last axis (reference: utils.py create_mask;
    mask_1d/mask_2d_greedy/mask_2d_best all reduce to n-of-m selection —
    the 2d variants differ only in tie-breaking)."""
    arr = np.asarray(tensor._data) if isinstance(tensor, Tensor) else np.asarray(tensor)
    flat = arr.reshape(-1, arr.shape[-1])
    mask = np.stack([_mask_1d_nm(row, n, m) for row in flat])
    return mask.reshape(arr.shape).astype(arr.dtype)


def check_sparsity(tensor, n=2, m=4) -> bool:
    arr = np.asarray(tensor._data) if isinstance(tensor, Tensor) else np.asarray(tensor)
    flat = arr.reshape(-1)
    pad = (-len(flat)) % m
    v = np.concatenate([flat, np.zeros(pad, arr.dtype)]).reshape(-1, m)
    return bool((np.count_nonzero(v, axis=1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _excluded_layers.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded_layers.clear()


def _supported_params(model: Layer):
    for name, p in model.named_parameters():
        if p is None or p.ndim < 2:
            continue
        if name in _excluded_layers:
            continue
        # prune matmul-style weights only (reference supports fc/conv)
        if p.shape[-1] % 4 != 0:
            continue
        yield name, p


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to supported parameters and remember them so
    `decorate`d optimizers keep re-applying after each step."""
    pruned = {}
    for name, p in _supported_params(model):
        mask = create_mask(p, mask_algo, n, m)
        p._data = p._data * jnp.asarray(mask)
        if with_mask:
            _masks[name] = (p, jnp.asarray(mask))
        pruned[name] = calculate_density(p)
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply the recorded masks (reference:
    asp.py decorate → OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step_with_masks(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for name, (p, mask) in _masks.items():
            p._data = p._data * mask
        return out

    optimizer.step = step_with_masks
    optimizer._asp_decorated = True
    return optimizer
