"""incubate.distributed (reference: python/paddle/incubate/distributed/ —
MoE models; the fleet/PS pieces live under paddle.distributed here)."""
from . import models  # noqa: F401

__all__ = ["models"]
