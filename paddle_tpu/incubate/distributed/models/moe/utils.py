"""MoE routing utilities (reference:
python/paddle/distributed/models/moe/utils.py — `_number_count`,
`_assign_pos`, `_random_routing`, `_limit_by_capacity`,
`_prune_gate_by_capacity` over the CUDA ops number_count / assign_pos /
limit_by_capacity / prune_gate_by_capacity / random_routing).

TPU-native formulations: every op is a static-shape jnp scatter/cumsum
(jit-safe), replacing the reference's hand-CUDA counters. Also exported
without the underscore at `paddle_tpu.distributed.utils` (the import path
the reference docstrings use).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.dispatch import apply, unwrap
from .....core.tensor import Tensor

__all__ = [
    "_number_count", "_assign_pos", "_random_routing",
    "_limit_by_capacity", "_prune_gate_by_capacity",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _occurrence_rank(flat):
    """occ[i] = how many earlier positions hold the same value (the
    reference kernels' atomic-counter arrival order). O(N log N) via a
    stable sort: ties keep arrival order, so within each equal-value run
    the k-th element is the k-th arrival — its rank is its offset from
    the run's start (searchsorted of the sorted values against
    themselves). An N x N one-hot formulation would be 4 GB at 64k
    tokens; this is jit-static and linear in memory."""
    n = flat.shape[0]
    order = jnp.argsort(flat, stable=True)                  # [N]
    sorted_vals = flat[order]
    run_start = jnp.searchsorted(sorted_vals, sorted_vals, side="left")
    occ_sorted = jnp.arange(n, dtype=run_start.dtype) - run_start
    return jnp.zeros((n,), occ_sorted.dtype).at[order].set(occ_sorted)


def _number_count(numbers, upper_range):
    """Per-expert token counts from gate indices (number_count op):
    out[e] = how many entries of `numbers` equal e, length upper_range."""
    def fn(nums):
        flat = nums.reshape(-1)
        valid = (flat >= 0) & (flat < upper_range)
        idx = jnp.where(valid, flat, 0)
        ones = valid.astype(nums.dtype)
        return jnp.zeros((upper_range,), nums.dtype).at[idx].add(ones)

    out = apply(fn, numbers, name="number_count")
    out.stop_gradient = True
    return out


def _assign_pos(x, cum_count):
    """Token indices gathered into expert-sorted slot order (assign_pos
    op). cum_count is the INCLUSIVE per-expert cumsum of counts; matching
    the reference kernel, each token is placed by decrementing its
    expert's cumulative counter, so tokens appear in reverse arrival
    order within an expert's segment."""
    def fn(nums, cum):
        flat = nums.reshape(-1)
        occ = _occurrence_rank(flat)
        slots = cum[flat] - 1 - occ
        total = flat.shape[0]
        out = jnp.zeros((total,), cum.dtype)
        return out.at[slots].set(jnp.arange(total, dtype=cum.dtype))

    out = apply(fn, x, cum_count, name="assign_pos")
    out.stop_gradient = True
    return out


def _random_routing(topk_idx, topk_value, prob, topk=2):
    """Drop the 2nd expert when its gate weight is too small vs a random
    draw (random_routing op): out[i][1] = -1 where 2*value[i][1] < prob[i].
    Only topk=2 exists in the reference."""
    if topk != 2:
        raise RuntimeError("only topk=2 is supported now")

    def fn(idx, val, p):
        drop = topk * val[:, topk - 1] < p
        col = jnp.where(drop, jnp.asarray(-1, idx.dtype), idx[:, topk - 1])
        return idx.at[:, topk - 1].set(col)

    out = apply(fn, topk_idx, topk_value, prob, name="random_routing")
    out.stop_gradient = True
    return out


def _limit_by_capacity(expert_count, capacity, n_worker):
    """Clamp per-(worker, expert) counts so each expert's TOTAL across
    workers fits `capacity` (limit_by_capacity op): capacity is consumed
    greedily in worker order — worker w keeps
    min(count, capacity_left_after_workers_<w)."""
    def fn(ec, cap):
        grid = ec.reshape(n_worker, -1)                     # [W, E]
        cum = jnp.cumsum(grid, axis=0)
        allowed = jnp.minimum(cum, cap[None, :].astype(cum.dtype))
        prev = jnp.concatenate(
            [jnp.zeros_like(allowed[:1]), allowed[:-1]], axis=0)
        return (allowed - prev).astype(ec.dtype).reshape(ec.shape)

    out = apply(fn, expert_count, capacity, name="limit_by_capacity")
    out.stop_gradient = True
    return out


def _prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker):
    """Invalidate (set to -1) gate assignments beyond each expert's
    remaining budget (prune_gate_by_capacity op): tokens consume
    expert_count[gate] in arrival order."""
    def fn(gate, ec):
        flat = gate.reshape(-1)
        occ = _occurrence_rank(flat)
        keep = occ < ec[flat]
        return jnp.where(keep, flat,
                         jnp.asarray(-1, gate.dtype)).reshape(gate.shape)

    out = apply(fn, gate_idx, expert_count, name="prune_gate_by_capacity")
    out.stop_gradient = True
    return out
