"""MoELayer (reference moe_layer.py:260): gate -> capacity dispatch ->
experts -> combine. See package docstring for the TPU-native dispatch."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....core.dispatch import apply
from .....nn.layer import Layer
from .....nn import container as nn_container
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]

_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


class MoELayer(Layer):
    """gate -> dispatch -> experts -> combine (reference MoELayer).

    experts: list/LayerList of expert Layers, each [*, d_model] ->
    [*, d_model]. gate: name ('naive' | 'gshard' | 'switch'), a BaseGate
    instance, or a dict {"type": name, ...kwargs}. The GShard aux loss of
    the last forward is exposed as `self.l_aux` (and on the gate's
    `.loss`), matching the reference training recipe.
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = nn_container.LayerList(list(experts))
        self.experts = experts
        num_expert = len(experts)
        if gate is None:
            gate = "gshard"
        if isinstance(gate, dict):
            cfg = dict(gate)
            gate = cfg.pop("type", "gshard")
            kwargs.update(cfg)
        if isinstance(gate, str):
            cls = _GATES[gate]
            gate = cls(d_model, num_expert,
                       top_k=(1 if cls is SwitchGate else top_k))
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a name or BaseGate, got {gate!r}")
        self.gate = gate
        self.top_k = gate.top_k
        self.l_aux = None

    def forward(self, inp):
        orig_shape = inp.shape
        x = inp.reshape([-1, self.d_model]) if len(orig_shape) != 2 else inp
        logits = self.gate(x)                       # [T, E]
        E = len(self.experts)
        T = x.shape[0]
        capacity = max(1, int(2.0 * T * self.top_k / E))
        top_k = self.top_k

        def route(lg):
            probs = jax.nn.softmax(lg, -1)
            vals, idx = jax.lax.top_k(probs, top_k)        # [T, k]
            disp = jnp.zeros((T, E, capacity), probs.dtype)
            combine = jnp.zeros((T, E, capacity), probs.dtype)
            # running per-expert slot counter ACROSS the k passes — a token
            # routed to expert e at k=1 must not collide with slots the
            # k=0 pass already filled
            base = jnp.zeros((E,), probs.dtype)
            for k in range(top_k):
                e_k = idx[:, k]
                onehot = jax.nn.one_hot(e_k, E, dtype=probs.dtype)  # [T, E]
                # position of each token within its expert's capacity
                pos = (base[None, :] + jnp.cumsum(onehot, 0)
                       - onehot) * onehot                           # [T, E]
                in_cap = (pos < capacity)
                sel = onehot * in_cap
                p = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
                disp_k = sel[:, :, None] * jax.nn.one_hot(
                    p, capacity, dtype=probs.dtype)
                disp = disp + disp_k
                combine = combine + disp_k * vals[:, k][:, None, None]
                base = base + onehot.sum(0)
            # GShard aux loss: E * mean(fraction) . mean(prob) per expert
            frac = jnp.mean(jax.nn.one_hot(idx[:, 0], E, dtype=probs.dtype),
                            axis=0)
            mean_p = probs.mean(0)
            aux = E * jnp.sum(frac * mean_p)
            return disp, combine, aux

        disp_t, comb_t, aux_t = apply(route, logits, name="moe_route")
        # dispatch: [T,E,C] x [T,H] -> per-expert slices [E, C, H]
        expert_in = apply(lambda d, a: jnp.einsum("tec,th->ech", d, a),
                          disp_t, x, name="moe_dispatch")
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        stacked = apply(lambda *os: jnp.stack(os), *outs, name="moe_stack")
        y = apply(lambda c, s: jnp.einsum("tec,ech->th", c, s),
                  comb_t, stacked, name="moe_combine")
        self.l_aux = aux_t
        self.gate.loss = aux_t
        if len(orig_shape) != 2:
            y = y.reshape(list(orig_shape))
        return y
