"""MoELayer (reference moe_layer.py:260): gate -> capacity dispatch ->
experts -> combine. Dispatch/combine/aux come from the SAME routing core
as parallel/moe.py (_routing: choice-major capacity assignment, GShard
aux, normalized top-k combine) so the two MoE paths cannot drift."""
from __future__ import annotations

import jax.numpy as jnp

from .....core.dispatch import apply
from .....nn.layer import Layer
from .....nn import container as nn_container
from .....parallel.moe import _routing, moe_capacity
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]

_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


class MoELayer(Layer):
    """gate -> dispatch -> experts -> combine (reference MoELayer).

    experts: list/LayerList of expert Layers, each [*, d_model] ->
    [*, d_model]. gate: name ('naive' | 'gshard' | 'switch'), a BaseGate
    instance, or a dict {"type": name, ...gate kwargs} (forwarded to the
    gate constructor). The GShard aux loss of the last forward is exposed
    as `self.l_aux` (and on the gate's `.loss`).
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = nn_container.LayerList(list(experts))
        self.experts = experts
        num_expert = len(experts)
        if gate is None:
            gate = "gshard"
        gate_kwargs = {}
        if isinstance(gate, dict):
            gate_kwargs = dict(gate)
            gate = gate_kwargs.pop("type", "gshard")
        if isinstance(gate, str):
            cls = _GATES[gate]
            gate_kwargs.setdefault(
                "top_k", 1 if cls is SwitchGate else top_k)
            gate = cls(d_model, num_expert, **gate_kwargs)
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a name or BaseGate, got {gate!r}")
        if gate.top_k > num_expert:
            raise ValueError(
                f"top_k ({gate.top_k}) cannot exceed the number of experts "
                f"({num_expert})")
        self.gate = gate
        self.top_k = gate.top_k
        self.l_aux = None

    def forward(self, inp):
        orig_shape = inp.shape
        x = inp.reshape([-1, self.d_model]) if len(orig_shape) != 2 else inp
        logits = self.gate(x)                       # [T, E]
        E = len(self.experts)
        T = x.shape[0]
        # gate-configured capacity factor when present (GShard/Switch
        # capacity=(train_cf, eval_cf)); reference default otherwise
        # capacity_factor convention matches parallel/moe.moe_capacity:
        # capacity = ceil(cf * top_k * T / E)
        cf = getattr(self.gate, "capacity", None)
        factor = (cf[0] if self.training else cf[1]) if cf else 2.0
        capacity = moe_capacity(T, E, self.top_k, factor)
        top_k = self.top_k

        def route(lg):
            return _routing(lg, E, top_k, capacity)

        disp_t, comb_t, aux_t = apply(route, logits, name="moe_route")
        # dispatch: [T,E,C] x [T,H] -> per-expert slices [E, C, H]
        expert_in = apply(lambda d, a: jnp.einsum("tec,th->ech", d, a),
                          disp_t, x, name="moe_dispatch")
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        stacked = apply(lambda *os: jnp.stack(os), *outs, name="moe_stack")
        y = apply(lambda c, s: jnp.einsum("tec,ech->th", c, s),
                  comb_t, stacked, name="moe_combine")
        self.l_aux = aux_t
        self.gate.loss = aux_t
        if len(orig_shape) != 2:
            y = y.reshape(list(orig_shape))
        return y
