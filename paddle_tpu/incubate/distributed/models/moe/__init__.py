"""Mixture-of-Experts layer + gates (reference:
python/paddle/incubate/distributed/models/moe/ — moe_layer.py:260 MoELayer
with gate/{naive,gshard,switch}_gate.py, dispatched via
global_scatter/global_gather all-to-alls).

TPU-native: routing/dispatch ride the same capacity-factor machinery as
parallel/moe.py (one lax.all_to_all each way on the 'ep' mesh axis under
shard_map; dense one-hot dispatch/combine einsums locally). Experts are
arbitrary Layers: each expert runs on its [capacity, d_model] slice, so
per-token FLOPs are k * cf * expert_cost — independent of num_experts.
"""
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate
from .moe_layer import MoELayer
from . import utils

__all__ = ["MoELayer", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]
