"""MoE gates (reference: incubate/distributed/models/moe/gate/*.py)."""
from __future__ import annotations

from .....nn.layer import Layer
from .....nn.common import Linear

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


class BaseGate(Layer):
    """Gate contract (base_gate.py): maps [T, d_model] -> routing logits
    [T, num_expert * world_size]; top_k set by subclass."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2):
        super().__init__()
        self.d_model = d_model
        self.tot_expert = num_expert * world_size
        self.top_k = top_k
        self.loss = None

    def forward(self, x):
        raise NotImplementedError


class NaiveGate(BaseGate):
    """Plain linear top-k gate (naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2):
        super().__init__(d_model, num_expert, world_size, top_k)
        self.gate = Linear(d_model, self.tot_expert)

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """Naive gate + GShard load-balance auxiliary loss (gshard_gate.py);
    the aux loss of the last forward lands in `self.loss`."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, top_k)
        self.capacity = capacity


class SwitchGate(NaiveGate):
    """Top-1 switch routing (switch_gate.py): logits get uniform noise of
    width switch_eps during training (load-balancing jitter); top_k is
    always 1 (the Switch contract — an explicit larger value errors)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        if top_k != 1:
            raise ValueError("SwitchGate is top-1 routing by definition")
        super().__init__(d_model, num_expert, world_size, top_k=1)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, x):
        out = self.gate(x)
        if self.training and self.switch_eps:
            from .....core import random as _rng
            from .....core.dispatch import apply
            import jax
            import jax.numpy as jnp

            key = _rng.next_key()

            def jitter(lg):
                noise = jax.random.uniform(
                    key, lg.shape, lg.dtype,
                    minval=1.0 - self.switch_eps,
                    maxval=1.0 + self.switch_eps)
                return lg * noise

            out = apply(jitter, out, name="switch_jitter")
        return out
