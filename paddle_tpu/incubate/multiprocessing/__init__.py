"""paddle.incubate.multiprocessing parity (reference:
python/paddle/incubate/multiprocessing/__init__.py — the stdlib
multiprocessing namespace with paddle-Tensor-aware ForkingPickler
reductions installed).
"""
from multiprocessing import *  # noqa: F401,F403
import multiprocessing

from .reductions import init_reductions

__all__ = list(multiprocessing.__all__)  # type: ignore[attr-defined]

init_reductions()
