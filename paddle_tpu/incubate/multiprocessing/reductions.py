"""Tensor reductions for multiprocessing (reference:
python/paddle/incubate/multiprocessing/reductions.py — ForkingPickler
reductions that move LoDTensor payloads through shared memory / CUDA IPC
instead of pickling bytes).

TPU-native re-design: device buffers are not IPC-shareable across host
processes (single controller owns the chip), so the zero-copy path is
host-side: tensors above a small threshold are staged into POSIX shared
memory (`multiprocessing.shared_memory`) and rebuilt as host tensors in
the consumer; small tensors pickle by value.

Lifetime: the PRODUCER owns every segment it created; consumers only
close their mapping, so a payload can be deserialized any number of
times (fan-out to N workers, redelivery after a crash). Producer-side
segments are bounded by an LRU of PTPU_SHM_CACHE_SEGMENTS (default 64):
beyond that the oldest segment is unlinked. A payload older than the
window that was never delivered therefore fails to rebuild
(FileNotFoundError) — raise the env var for deep prefetch queues; the
window never evicts the segment just created.

Producer exit uses a refcounted handshake so the common
"short-lived producer queues a tensor and exits" pattern cannot race
delivery: each consumer leaves a 1-byte ack segment after a successful
rebuild; exit cleanup reaps acked segments immediately and lingers up
to PTPU_SHM_LINGER seconds (default 2.0, 0 disables) for in-flight
unacked ones before unlinking them too (the reference's
file_system-strategy shape with a bounded grace window).
"""
from __future__ import annotations

import atexit
import os
import time
from collections import OrderedDict
from multiprocessing.reduction import ForkingPickler

import numpy as np

_SHM_MIN_BYTES = 1 << 16  # below this, copying beats shm setup
_ACK_SUFFIX = "_ack"

# segments this process created, oldest-first (producer-owned cleanup)
_PRODUCED: "OrderedDict[str, object]" = OrderedDict()


def _max_segments():
    # clamp to >= 1: eviction must never reclaim the segment just created
    # for the payload being serialized
    return max(1, int(os.environ.get("PTPU_SHM_CACHE_SEGMENTS", "64")))


def _unlink_quiet(shm):
    try:
        shm.close()
        shm.unlink()
    except (FileNotFoundError, OSError):
        pass


def _untrack(shm):
    """CPython <= 3.12 registers attached segments with the resource
    tracker too; without unregistering, the tracker re-unlinks (and
    warns about) segments this process merely peeked at."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # ptpu-check[silent-except]: resource_tracker internals differ across
        # py versions — unregister is a cosmetic leak-warning fix
        pass


def _unlink_by_name(name):
    from multiprocessing import shared_memory

    try:
        seg = shared_memory.SharedMemory(name=name)
    except (FileNotFoundError, OSError):
        return
    _untrack(seg)
    _unlink_quiet(seg)


def _acked(name):
    from multiprocessing import shared_memory

    try:
        m = shared_memory.SharedMemory(name=name + _ACK_SUFFIX)
    except (FileNotFoundError, OSError):
        return False
    _untrack(m)
    m.close()
    return True


def _cleanup_produced():
    linger = float(os.environ.get("PTPU_SHM_LINGER", "2.0"))
    deadline = time.monotonic() + linger
    pending = dict(_PRODUCED)
    _PRODUCED.clear()
    # reap acked segments first (no wait); linger only while some payload
    # is still in flight — a consumer that rebuilds during the grace
    # window acks and releases us early
    while pending:
        for name in [n for n in pending if _acked(n)]:
            _unlink_quiet(pending.pop(name))
            _unlink_by_name(name + _ACK_SUFFIX)
        if not pending or time.monotonic() >= deadline:
            break
        time.sleep(0.05)
    for name, shm in pending.items():
        _unlink_quiet(shm)
        _unlink_by_name(name + _ACK_SUFFIX)


atexit.register(_cleanup_produced)


def _rebuild_from_shm(shm_name, shape, dtype_name):
    from multiprocessing import shared_memory

    from ...core.tensor import Tensor

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.ndarray(shape, dtype=np.dtype(dtype_name), buffer=shm.buf)
        out = Tensor(np.array(arr))  # own the data before the shm closes
    finally:
        shm.close()  # close only: the producer unlinks at its exit
    # delivery ack: lets the producer's exit cleanup reap this segment
    # without waiting out the linger window
    try:
        m = shared_memory.SharedMemory(name=shm_name + _ACK_SUFFIX,
                                       create=True, size=1)
        try:
            from multiprocessing import resource_tracker

            # the producer owns the marker's unlink; without this, the
            # consumer's resource tracker reclaims it at consumer exit
            resource_tracker.unregister(m._name, "shared_memory")
        except Exception:  # ptpu-check[silent-except]: same resource_tracker best-effort as
            # above
            pass
        m.close()
    except FileExistsError:
        pass  # fan-out: an earlier consumer already acked
    except OSError:
        pass
    return out


def _rebuild_small(payload, shape, dtype_name):
    from ...core.tensor import Tensor

    return Tensor(np.frombuffer(payload, dtype=np.dtype(dtype_name)
                                ).reshape(shape).copy())


def _reduce_tensor(tensor):
    """Stage the host view in shm (large) or by value (small). Dtypes
    travel by NAME (ml_dtypes registers bfloat16 with numpy, so
    np.dtype("bfloat16") round-trips; the .str code would rebuild as
    void)."""
    arr = np.asarray(tensor.numpy())
    if arr.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
        dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
        dst[...] = arr
        _PRODUCED[shm.name] = shm  # alive until LRU eviction/atexit unlink
        while len(_PRODUCED) > _max_segments():
            name, old = next(iter(_PRODUCED.items()))
            if name == shm.name:       # never evict the payload being built
                break
            _PRODUCED.pop(name)
            _unlink_quiet(old)
            _unlink_by_name(name + _ACK_SUFFIX)
        return _rebuild_from_shm, (shm.name, arr.shape, arr.dtype.name)
    return _rebuild_small, (arr.tobytes(), arr.shape, arr.dtype.name)


def init_reductions():
    from ...core.tensor import Tensor

    ForkingPickler.register(Tensor, _reduce_tensor)
    from ...nn.layer import Parameter

    ForkingPickler.register(Parameter, _reduce_tensor)
