"""Tensor reductions for multiprocessing (reference:
python/paddle/incubate/multiprocessing/reductions.py — ForkingPickler
reductions that move LoDTensor payloads through shared memory / CUDA IPC
instead of pickling bytes).

TPU-native re-design: device buffers are not IPC-shareable across host
processes (single controller owns the chip), so the zero-copy path is
host-side: tensors above a small threshold are staged into POSIX shared
memory (`multiprocessing.shared_memory`) and rebuilt as host tensors in
the consumer; small tensors pickle by value.

Lifetime: the PRODUCER owns every segment it created; consumers only
close their mapping, so a payload can be deserialized any number of
times (fan-out to N workers, redelivery after a crash). Producer-side
segments are bounded by an LRU of PTPU_SHM_CACHE_SEGMENTS (default 64):
beyond that the oldest segment is unlinked. A payload older than the
window that was never delivered therefore fails to rebuild
(FileNotFoundError) — raise the env var for deep prefetch queues; the
window never evicts the segment just created. Everything left unlinks at
interpreter exit (the reference's file_system-strategy shape, same
staleness tradeoff).
"""
from __future__ import annotations

import atexit
import os
from collections import OrderedDict
from multiprocessing.reduction import ForkingPickler

import numpy as np

_SHM_MIN_BYTES = 1 << 16  # below this, copying beats shm setup

# segments this process created, oldest-first (producer-owned cleanup)
_PRODUCED: "OrderedDict[str, object]" = OrderedDict()


def _max_segments():
    # clamp to >= 1: eviction must never reclaim the segment just created
    # for the payload being serialized
    return max(1, int(os.environ.get("PTPU_SHM_CACHE_SEGMENTS", "64")))


def _cleanup_produced():
    for shm in _PRODUCED.values():
        try:
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        except OSError:
            pass
    _PRODUCED.clear()


atexit.register(_cleanup_produced)


def _rebuild_from_shm(shm_name, shape, dtype_name):
    from multiprocessing import shared_memory

    from ...core.tensor import Tensor

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.ndarray(shape, dtype=np.dtype(dtype_name), buffer=shm.buf)
        out = Tensor(np.array(arr))  # own the data before the shm closes
    finally:
        shm.close()  # close only: the producer unlinks at its exit
    return out


def _rebuild_small(payload, shape, dtype_name):
    from ...core.tensor import Tensor

    return Tensor(np.frombuffer(payload, dtype=np.dtype(dtype_name)
                                ).reshape(shape).copy())


def _reduce_tensor(tensor):
    """Stage the host view in shm (large) or by value (small). Dtypes
    travel by NAME (ml_dtypes registers bfloat16 with numpy, so
    np.dtype("bfloat16") round-trips; the .str code would rebuild as
    void)."""
    arr = np.asarray(tensor.numpy())
    if arr.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
        dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
        dst[...] = arr
        _PRODUCED[shm.name] = shm  # alive until LRU eviction/atexit unlink
        while len(_PRODUCED) > _max_segments():
            name, old = next(iter(_PRODUCED.items()))
            if name == shm.name:       # never evict the payload being built
                break
            _PRODUCED.pop(name)
            try:
                old.close()
                old.unlink()
            except (FileNotFoundError, OSError):
                pass
        return _rebuild_from_shm, (shm.name, arr.shape, arr.dtype.name)
    return _rebuild_small, (arr.tobytes(), arr.shape, arr.dtype.name)


def init_reductions():
    from ...core.tensor import Tensor

    ForkingPickler.register(Tensor, _reduce_tensor)
    from ...nn.layer import Parameter

    ForkingPickler.register(Parameter, _reduce_tensor)
