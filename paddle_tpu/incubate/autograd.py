"""incubate.autograd (reference: python/paddle/incubate/autograd — prim
vjp/jvp API). TPU-native: jax transforms ARE the primitive system."""
from ..autograd.functional import vjp, jvp, jacobian, hessian

Jacobian = jacobian
Hessian = hessian

__all__ = ["vjp", "jvp", "jacobian", "hessian", "Jacobian", "Hessian"]
