"""incubate.autograd (reference: python/paddle/incubate/autograd —
functional.py Jacobian/Hessian lazy matrices, primapi.forward_grad/grad,
primx enable_prim mode).

TPU-native position: the reference lowers programs to a hand-maintained
primitive op set (primops.py) so linearize/transpose rules can run as
program passes; here jax's jaxpr IS that primitive IR and jvp/vjp ARE the
linearize/transpose passes. What this module adds over re-exports:

- Jacobian / Hessian: lazy matrix views with reference indexing semantics
  (rows computed on demand via one vjp per requested row, not the dense
  jacobian up front).
- forward_grad / grad_: the primapi surface (forward- and reverse-mode
  grads of a function at concrete inputs).
- enable_prim / disable_prim / prim_enabled: mode flag kept for API
  parity; both modes execute the same jax transforms (there is no
  separate non-primitive path to fall back to).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.functional import vjp, jvp, jacobian, hessian
from ..core.tensor import Tensor

__all__ = ["vjp", "jvp", "jacobian", "hessian", "Jacobian", "Hessian",
           "forward_grad", "grad_", "enable_prim", "disable_prim",
           "prim_enabled"]

_prim = False


def enable_prim():
    global _prim
    _prim = True


def disable_prim():
    global _prim
    _prim = False


def prim_enabled():
    return _prim


def _unwrap(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._data if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]


def _wrap_fn(func):
    def fn(*arrays):
        out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (list, tuple)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out
    return fn


class Jacobian:
    """Lazy Jacobian matrix J[i, j] = d out_i / d in_j (reference
    functional.py Jacobian: 2-D view over flattened out/in, rows computed
    on demand)."""

    def __init__(self, func, xs, is_batched=False):
        self._arrays = _unwrap(xs)
        self._fn = _wrap_fn(func)
        out, self._pull = jax.vjp(self._fn, *self._arrays)
        if isinstance(out, tuple):
            raise ValueError("Jacobian expects a single-output function")
        self._out = out
        self._rows = int(out.size)
        self._cols = int(sum(a.size for a in self._arrays))
        self._cache = {}

    @property
    def shape(self):
        return (self._rows, self._cols)

    def _row(self, i):
        if i not in self._cache:
            seed = jnp.zeros(self._out.shape, self._out.dtype
                             ).reshape(-1).at[i].set(1.0).reshape(self._out.shape)
            cts = self._pull(seed)
            self._cache[i] = jnp.concatenate([c.reshape(-1) for c in cts])
        return self._cache[i]

    def __getitem__(self, idx):
        if isinstance(idx, tuple):
            r, c = idx
        else:
            r, c = idx, slice(None)
        rows = range(*r.indices(self._rows)) if isinstance(r, slice) else [r]
        mat = jnp.stack([self._row(i) for i in rows])
        out = mat[:, c]
        if not isinstance(r, slice):
            out = out[0]
        return Tensor(out)

    def numpy(self):
        import numpy as np

        return np.asarray(self[:, :]._data)


class Hessian(Jacobian):
    """Lazy Hessian of a scalar function (reference functional.py Hessian =
    Jacobian of the gradient)."""

    def __init__(self, func, xs, is_batched=False):
        arrays = _unwrap(xs)
        fn = _wrap_fn(func)

        def grad_vec(*ts):
            arrs = [t._data for t in ts]
            g = jax.grad(lambda *a: jnp.sum(fn(*a)),
                         argnums=tuple(range(len(arrs))))(*arrs)
            return Tensor(jnp.concatenate([x.reshape(-1) for x in g]))

        super().__init__(grad_vec, xs)


def forward_grad(func, xs, v=None):
    """Forward-mode grads (reference primapi.forward_grad): jvp of func at
    xs with tangent v (defaults to ones)."""
    _, tangents = jvp(func, xs, v)
    return tangents


def grad_(func, xs, v=None):
    """Reverse-mode grads (reference primapi.grad)."""
    _, cts = vjp(func, xs, v)
    return cts
