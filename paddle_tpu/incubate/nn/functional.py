"""incubate.nn.functional (reference: python/paddle/incubate/nn/functional/
— the functional forms of the fused layers: fused_matmul_bias /
fused_linear (fused_gemm_epilogue), fused_bias_dropout_residual_layer_norm,
fused_feedforward, fused_multi_head_attention, fused_ec_moe).

TPU-native: each "fused op" is expressed once as a pure jnp composition —
XLA's fusion pass produces the same fused kernels the reference hand-wrote
in CUDA (gemm+bias epilogue, bias+dropout+residual+LN chains), so these
are thin, correct-by-construction compositions rather than kernel
bindings.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...nn import functional as F
from . import fused_ec_moe  # re-export (defined alongside the layer)

__all__ = ["fused_matmul_bias", "fused_linear", "fused_ec_moe",
           "fused_bias_dropout_residual_layer_norm", "fused_feedforward",
           "fused_multi_head_attention"]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (reference fused_gemm_epilogue op)."""
    def fn(a, b, *maybe_bias):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    args = [x, y] + ([bias] if bias is not None else [])
    return apply(fn, *args, name="fused_matmul_bias")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """(x + bias) -> dropout -> + residual -> LayerNorm (reference
    fused_bias_dropout_residual_layer_norm op)."""
    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training, mode=mode)
    y = y + residual
    shape = [y.shape[-1]]
    return F.layer_norm(y, normalized_shape=shape, weight=ln_scale,
                        bias=ln_bias, epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", name=None):
    """Transformer FFN block with residual + LN (reference
    fused_feedforward_op)."""
    residual = x
    shape = [x.shape[-1]]
    if pre_layer_norm:
        x = F.layer_norm(x, normalized_shape=shape, weight=ln1_scale,
                         bias=ln1_bias, epsilon=ln1_epsilon)
    h = fused_linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, normalized_shape=shape, weight=ln2_scale,
                           bias=ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Fused attention block (reference fused_attention_op): optional
    pre-LN -> qkv projection -> flash attention -> out projection ->
    dropout -> residual -> optional post-LN.

    qkv_weight: [3, num_heads, head_dim, embed_dim] (reference layout) or
    [embed_dim, 3*embed_dim].
    """
    from ...ops.pallas_ops import flash_attention

    residual = x
    B, S, E = x.shape
    shape = [E]
    if pre_layer_norm:
        x = F.layer_norm(x, normalized_shape=shape, weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    w = qkv_weight
    if w.ndim == 4:   # [3, H, D, E] reference layout -> [E, 3HD]
        nh = w.shape[1]
        hd = w.shape[2]
        w = w.reshape([3 * nh * hd, E]).transpose([1, 0])
    else:
        if num_heads is None:
            raise ValueError("num_heads required with 2-D qkv_weight")
        nh = num_heads
        hd = E // nh
    qkv = fused_linear(x, w, qkv_bias)
    q, k, v = qkv.reshape([B, S, 3, nh, hd]).unbind(axis=2)
    attn = flash_attention(q, k, v, attn_mask=attn_mask,
                           is_causal=attn_mask is None)
    out = fused_linear(attn.reshape([B, S, E]), linear_weight, linear_bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, normalized_shape=shape, weight=ln_scale,
                           bias=ln_bias, epsilon=ln_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, time_step=None, attn_mask=None,
        dropout_rate=0.0, activation="gelu", training=False,
        mode="upscale_in_train", trans_qkvw=True, ring_id=-1, name=None):
    """Functional fused multi-transformer (reference
    incubate/nn/functional/fused_transformer.py fused_multi_transformer ->
    fused_multi_transformer_op.cu). Builds the FusedMultiTransformer layer
    over the given per-layer weights and runs it once, threading CacheKV.

    qkv_weights accepts the reference 4-D layout ([3, num_heads, head_dim,
    embed_dim] when trans_qkvw else [embed_dim, 3, num_heads, head_dim])
    or plain Linear-shaped [embed_dim, 3*embed_dim]."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor
    from .. import nn as _inc_nn

    def arr(t):
        return t._data if isinstance(t, Tensor) else jnp.asarray(t)

    e = x.shape[-1]
    num_layers = len(qkv_weights)
    q0 = arr(qkv_weights[0])
    if q0.ndim == 4:
        nh = q0.shape[1] if trans_qkvw else q0.shape[2]
    elif cache_kvs is not None:
        nh = arr(cache_kvs[0]).shape[2]
    else:
        raise ValueError(
            "2-D qkv weights need cache_kvs to infer num_heads "
            "(or pass the reference 4-D qkv layout)")
    f = arr(ffn1_weights[0]).shape[-1]
    if not pre_layer_norm:
        raise ValueError(
            "fused_multi_transformer on this backend is pre-LN only "
            "(FusedMultiTransformer contract; reference's post-LN variant "
            "is unsupported)")

    from ...framework.compat import LazyGuard

    key = (e, nh, f, num_layers, epsilon, dropout_rate, activation)
    layer = _FMT_CACHE.get(key)
    if layer is None:
        _FMT_CACHE.clear()   # size-1 cache: decode loops reuse ONE geometry;
        #                      don't pin weight sets for stale geometries
        with LazyGuard():
            # zeros-init under the guard: every parameter is overwritten
            # below, so skip the random initializer work; the layer shell
            # is memoized per geometry — per-decode-step calls only pay
            # the weight rebinds
            layer = _inc_nn.FusedMultiTransformer(
                embed_dim=e, num_heads=nh, dim_feedforward=f,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=pre_layer_norm, num_layers=num_layers,
                epsilon=epsilon)
        _FMT_CACHE[key] = layer

    def qkv_2d(w):
        w = arr(w)
        if w.ndim == 4:
            if trans_qkvw:                 # [3, H, D, E] -> [E, 3HD]
                return w.reshape(-1, e).T
            return w.reshape(e, -1)        # [E, 3, H, D] -> [E, 3HD]
        return w

    for i in range(num_layers):
        blk = layer.layers[i]
        blk["ln1"].weight._set_data(arr(ln_scales[i]))
        blk["ln1"].bias._set_data(arr(ln_biases[i]))
        blk["qkv"].weight._set_data(qkv_2d(qkv_weights[i]))
        blk["qkv"].bias._set_data(arr(qkv_biases[i]).reshape(-1))
        blk["out"].weight._set_data(arr(linear_weights[i]))
        blk["out"].bias._set_data(arr(linear_biases[i]))
        blk["ln2"].weight._set_data(arr(ffn_ln_scales[i]))
        blk["ln2"].bias._set_data(arr(ffn_ln_biases[i]))
        blk["ffn1"].weight._set_data(arr(ffn1_weights[i]))
        blk["ffn1"].bias._set_data(arr(ffn1_biases[i]))
        blk["ffn2"].weight._set_data(arr(ffn2_weights[i]))
        blk["ffn2"].bias._set_data(arr(ffn2_biases[i]))
    # set the mode EVERY call: the memoized shell would otherwise keep a
    # previous call's eval() sticky and silently disable training dropout
    layer.train() if training else layer.eval()
    return layer(x, attn_mask=attn_mask, caches=cache_kvs,
                 time_step=time_step)


_FMT_CACHE = {}

__all__ += ["fused_multi_transformer"]
