"""incubate.nn fused layers (reference: python/paddle/incubate/nn/ —
FusedMultiHeadAttention, FusedFeedForward backed by fused_attention_op.cu /
fused_feedforward_op.cu). TPU-native: flash attention (Pallas) + XLA-fused
FFN."""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn.common import Linear, Dropout
from ...nn.norm import LayerNorm
from ...nn import container as nn_container
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedMultiTransformer"]


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon)
        self.post_ln = LayerNorm(embed_dim, epsilon)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...ops.manipulation import reshape, split

        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        b, s, _ = x.shape
        qkv = self.qkv(x)
        q, k, v = split(qkv, 3, axis=-1)
        q = reshape(q, [b, s, self.num_heads, self.head_dim])
        k = reshape(k, [b, s, self.num_heads, self.head_dim])
        v = reshape(v, [b, s, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training,
        )
        out = reshape(out, [b, s, self.embed_dim])
        out = self.dropout(self.out_proj(out))
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr, linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.activation = activation

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.linear2(self.dropout1(getattr(F, self.activation)(self.linear1(x))))
        x = residual + self.dropout2(x)
        if not self.normalize_before:
            x = self.ln(x)
        return x


class FusedMultiTransformer(Layer):
    """Stacked fused transformer decoder layers (reference:
    python/paddle/incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer over fused_multi_transformer_op.cu): pre-LN
    attention + FFN per layer, all heavy math in flash attention (Pallas)
    and XLA-fused matmuls."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise ValueError("FusedMultiTransformer is pre-LN (reference contract)")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.activation = activation
        layers = []
        for _ in range(num_layers):
            layers.append(nn_container.LayerDict({
                "ln1": LayerNorm(embed_dim, epsilon=epsilon),
                "qkv": Linear(embed_dim, 3 * embed_dim),
                "out": Linear(embed_dim, embed_dim),
                "ln2": LayerNorm(embed_dim, epsilon=epsilon),
                "ffn1": Linear(embed_dim, dim_feedforward),
                "ffn2": Linear(dim_feedforward, embed_dim),
            }))
        self.layers = nn_container.LayerList(layers)
        self.dropout = Dropout(dropout_rate)

    @staticmethod
    def _cached_attn(q, k, v, cache, t, mask=None):
        """Array-level CacheKV attention. cache: [2, B, H, S_max, D]
        (reference layout, fused_multi_transformer_op.cu:90); q/k/v:
        [B, S, H, D]; t = real current length of the cache (the chunk is
        written starting at t); mask broadcastable to [B, H, S, S_max].
        Returns (out, new_cache)."""
        from ...ops.pallas_ops import cached_attention_arrays

        kc = jnp.moveaxis(cache[0], 1, 2)        # -> [B, S_max, H, D]
        vc = jnp.moveaxis(cache[1], 1, 2)
        o, kc, vc = cached_attention_arrays(q, k, v, kc, vc, t, mask=mask)
        new_cache = jnp.stack(
            [jnp.moveaxis(kc, 2, 1), jnp.moveaxis(vc, 2, 1)])
        return o, new_cache

    def gen_cache(self, batch_size, max_length, dtype="float32"):
        """Allocate per-layer CacheKV tensors, reference layout
        [2, bsz, num_head, max_seq_len, head_dim]."""
        from ...core.tensor import Tensor

        shape = (2, batch_size, self.num_heads, max_length, self.head_dim)
        return [Tensor(jnp.zeros(shape, dtype)) for _ in range(self.num_layers)]

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        from ...core.dispatch import apply
        from ...ops.pallas_ops import flash_attention

        if caches is not None and len(caches) != self.num_layers:
            raise ValueError(
                f"caches must have one [2,B,H,S,D] tensor per layer "
                f"({self.num_layers}), got {len(caches)}")

        x = src
        B = None
        new_caches = []
        act = F.gelu if self.activation == "gelu" else F.relu
        for li, blk in enumerate(self.layers):
            h = blk["ln1"](x)
            qkv = blk["qkv"](h)
            if B is None:
                B, S, _ = qkv.shape
            q, k, v = qkv.reshape([B, S, 3, self.num_heads, self.head_dim]).unbind(axis=2)
            if caches is not None:
                t = 0 if time_step is None else time_step
                if attn_mask is not None:
                    # mask applies over cache positions: [B, H|1, S, S_max]
                    attn, new_cache = apply(
                        self._cached_attn, q, k, v, caches[li], t, attn_mask,
                        name="fused_cached_attention")
                else:
                    attn, new_cache = apply(
                        self._cached_attn, q, k, v, caches[li], t,
                        name="fused_cached_attention")
                # reference CacheKV is written in place by the fused op;
                # mirror that for eager callers while also returning the
                # updated caches for functional (traced) use
                caches[li]._data = new_cache._data
                new_caches.append(new_cache)
            else:
                attn = flash_attention(q, k, v, attn_mask=attn_mask,
                                       is_causal=attn_mask is None)
            x = x + self.dropout(blk["out"](attn.reshape([B, S, -1])))
            h = blk["ln2"](x)
            x = x + self.dropout(blk["ffn2"](act(blk["ffn1"](h))))
        if caches is not None:
            return x, new_caches
        return x
