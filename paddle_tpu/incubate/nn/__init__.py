"""incubate.nn fused layers (reference: python/paddle/incubate/nn/ —
FusedMultiHeadAttention, FusedFeedForward backed by fused_attention_op.cu /
fused_feedforward_op.cu). TPU-native: flash attention (Pallas) + XLA-fused
FFN."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn.common import Linear, Dropout
from ...nn.norm import LayerNorm
from ...nn.initializer import Constant
from ...nn import container as nn_container
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedMultiTransformer", "FusedMultiTransformerInt8",
           "FusedEcMoe", "fused_ec_moe", "functional"]


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon)
        self.post_ln = LayerNorm(embed_dim, epsilon)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...ops.manipulation import reshape, split

        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        b, s, _ = x.shape
        qkv = self.qkv(x)
        q, k, v = split(qkv, 3, axis=-1)
        q = reshape(q, [b, s, self.num_heads, self.head_dim])
        k = reshape(k, [b, s, self.num_heads, self.head_dim])
        v = reshape(v, [b, s, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training,
        )
        out = reshape(out, [b, s, self.embed_dim])
        out = self.dropout(self.out_proj(out))
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr, linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.activation = activation

    def _ffn(self, x):
        """act(x @ W1 + b1) @ W2 + b2 — via the row-blocked Pallas kernel
        (PTPU_PALLAS_FFN=1; the [tokens, I] intermediate never round-trips
        HBM in the forward) when geometry allows, else XLA."""

        if (self.activation in ("gelu", "relu")
                # dropout inactive: p == 0 or eval mode (identity)
                and (self.dropout1.p == 0.0 or not self.training)
                and self.linear2.bias is not None):
            from ...ops.pallas_ops import maybe_fused_ffn

            y = maybe_fused_ffn(x, self.linear1.weight, self.linear1.bias,
                                self.linear2.weight, self.activation)
            if y is not None:
                return y + self.linear2.bias
        return self.linear2(
            self.dropout1(getattr(F, self.activation)(self.linear1(x))))

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self._ffn(x)
        x = residual + self.dropout2(x)
        if not self.normalize_before:
            x = self.ln(x)
        return x


class FusedMultiTransformer(Layer):
    """Stacked fused transformer decoder layers (reference:
    python/paddle/incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer over fused_multi_transformer_op.cu): pre-LN
    attention + FFN per layer, all heavy math in flash attention (Pallas)
    and XLA-fused matmuls."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise ValueError("FusedMultiTransformer is pre-LN (reference contract)")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.activation = activation
        self.epsilon = epsilon
        self.dropout_rate = dropout_rate
        layers = []
        for _ in range(num_layers):
            layers.append(nn_container.LayerDict({
                "ln1": LayerNorm(embed_dim, epsilon=epsilon),
                "qkv": Linear(embed_dim, 3 * embed_dim),
                "out": Linear(embed_dim, embed_dim),
                "ln2": LayerNorm(embed_dim, epsilon=epsilon),
                "ffn1": Linear(embed_dim, dim_feedforward),
                "ffn2": Linear(dim_feedforward, embed_dim),
            }))
        self.layers = nn_container.LayerList(layers)
        self.dropout = Dropout(dropout_rate)

    @staticmethod
    def _fused_layer_decode(x2, lnw, lnb, wqkv, bqkv, wo, bo, cache, t,
                            nh, eps):
        """One layer's decode step through the fused Pallas kernel
        (reference: fused_multi_transformer_op.cu decode branch — this IS
        that op's shape): LN1 -> qkv -> ring cache write -> prefix
        attention -> out-proj -> residual in one launch. cache:
        [2, B, H, S_max, D] (reference layout), re-viewed flat for the
        kernel and repacked after."""
        from ...ops.pallas_ops import fused_decode_layer_arrays

        if cache.ndim == 4:
            # flat rings [2, B, S_max, H*D] (gen_cache(layout="flat")):
            # no relayout at all — the kernel's in-place aliasing donates
            # the REAL cache buffers
            y, kc2, vc2 = fused_decode_layer_arrays(
                x2, lnw, lnb, wqkv, bqkv, wo, bo, cache[0], cache[1], t,
                nh, eps)
            return y, jnp.stack([kc2, vc2])
        # reference layout [2, B, H, S_max, D]: per-step relayout copies —
        # the same cost the unfused _cached_attn path already pays, but it
        # defeats the kernel's buffer donation; prefer layout="flat"
        _, b, h, smax, d = cache.shape
        kc = jnp.moveaxis(cache[0], 1, 2).reshape(b, smax, h * d)
        vc = jnp.moveaxis(cache[1], 1, 2).reshape(b, smax, h * d)
        y, kc2, vc2 = fused_decode_layer_arrays(
            x2, lnw, lnb, wqkv, bqkv, wo, bo, kc, vc, t, nh, eps)
        new_cache = jnp.stack([
            jnp.moveaxis(kc2.reshape(b, smax, h, d), 2, 1),
            jnp.moveaxis(vc2.reshape(b, smax, h, d), 2, 1)])
        return y, new_cache

    def _fused_decode_ok(self, x, cache):
        """Gate: S==1 decode, no dropout, uniform bf16/f32 dtypes, kernel
        geometry (delegates to pallas_ops._fused_decode_layer_ok on the
        flat cache view). Int8 layers fail the dtype check naturally."""
        from ...ops.pallas_ops import _fused_decode_layer_ok

        if x.shape[1] != 1 or self.dropout_rate:
            return False
        blk = self.layers[0]
        w = getattr(blk["qkv"], "weight", None)
        E = x.shape[-1]
        if (w is None or getattr(w, "ndim", 0) != 2
                or tuple(w.shape) != (E, 3 * E)):
            return False   # freed/absent float weights (int8 subclass)
        if cache._data.ndim == 4:          # flat [2, B, Smax, H*D]
            _, b, smax, hd = cache.shape
        elif cache._data.ndim == 5:        # reference [2, B, H, Smax, D]
            _, b, h, smax, d = cache.shape
            hd = h * d
        else:
            return False
        # abstract view: the gate only reads shape/dtype
        kc_view = jax.ShapeDtypeStruct((b, smax, hd), cache._data.dtype)
        return _fused_decode_layer_ok(
            jax.ShapeDtypeStruct((b, hd), x.dtype), w._data, kc_view,
            kc_view, self.num_heads)

    @staticmethod
    def _cached_attn(q, k, v, cache, t, mask=None):
        """Array-level CacheKV attention. cache: [2, B, H, S_max, D]
        (reference layout, fused_multi_transformer_op.cu:90); q/k/v:
        [B, S, H, D]; t = real current length of the cache (the chunk is
        written starting at t); mask broadcastable to [B, H, S, S_max].
        Returns (out, new_cache)."""
        from ...ops.pallas_ops import cached_attention_arrays

        if cache.ndim == 4:          # flat rings [2, B, S_max, H*D]
            o, kc, vc = cached_attention_arrays(q, k, v, cache[0], cache[1],
                                                t, mask=mask)
            return o, jnp.stack([kc, vc])
        kc = jnp.moveaxis(cache[0], 1, 2)        # -> [B, S_max, H, D]
        vc = jnp.moveaxis(cache[1], 1, 2)
        o, kc, vc = cached_attention_arrays(q, k, v, kc, vc, t, mask=mask)
        new_cache = jnp.stack(
            [jnp.moveaxis(kc, 2, 1), jnp.moveaxis(vc, 2, 1)])
        return o, new_cache

    def gen_cache(self, batch_size, max_length, dtype="float32",
                  layout="reference"):
        """Allocate per-layer CacheKV tensors. layout="reference":
        [2, bsz, num_head, max_seq_len, head_dim] (the fused op's CUDA
        layout — kept as the compat default). layout="flat":
        [2, bsz, max_seq_len, num_head*head_dim] rings — the TPU-native
        form: decode writes stay contiguous one-row updates, and the
        fused decode kernel donates the cache buffers in place instead of
        round-tripping a relayout copy every layer every token."""
        from ...core.tensor import Tensor

        if layout == "flat":
            shape = (2, batch_size, max_length,
                     self.num_heads * self.head_dim)
        else:
            shape = (2, batch_size, self.num_heads, max_length,
                     self.head_dim)
        return [Tensor(jnp.zeros(shape, dtype)) for _ in range(self.num_layers)]

    def _proj(self, li, name, x):
        """One of the four heavy matmuls of layer li ('qkv', 'out',
        'ffn1', 'ffn2') — the quantized subclass reroutes exactly this."""
        return self.layers[li][name](x)

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        from ...core.dispatch import apply
        from ...ops.pallas_ops import flash_attention

        if caches is not None and len(caches) != self.num_layers:
            raise ValueError(
                f"caches must have one [2,B,H,S,D] tensor per layer "
                f"({self.num_layers}), got {len(caches)}")

        x = src
        B = None
        new_caches = []
        act = F.gelu if self.activation == "gelu" else F.relu
        # time_step None is prefill at position 0 — the fused kernel's
        # prefix contract needs t >= 1, so fused only on true decode steps
        use_fused = (caches is not None and attn_mask is None
                     and time_step is not None
                     and self._fused_decode_ok(x, caches[0]))
        for li, blk in enumerate(self.layers):
            if use_fused:
                # whole attention half in ONE Pallas launch per layer
                # (use_fused guarantees time_step is not None: the fused
                # kernel's prefix contract excludes the t=0 prefill)
                t = time_step
                Bq, _, E = x.shape
                y, new_cache = apply(
                    self._fused_layer_decode, x.reshape([Bq, E]),
                    blk["ln1"].weight, blk["ln1"].bias,
                    blk["qkv"].weight, blk["qkv"].bias,
                    blk["out"].weight, blk["out"].bias,
                    caches[li], t, nh=self.num_heads, eps=self.epsilon,
                    name="fused_decode_layer")
                caches[li]._data = new_cache._data
                new_caches.append(new_cache)
                x = y.reshape([Bq, 1, E])
                h = blk["ln2"](x)
                x = x + self._proj(li, "ffn2", act(self._proj(li, "ffn1", h)))
                continue
            h = blk["ln1"](x)
            qkv = self._proj(li, "qkv", h)
            if B is None:
                B, S, _ = qkv.shape
            q, k, v = qkv.reshape([B, S, 3, self.num_heads, self.head_dim]).unbind(axis=2)
            if caches is not None:
                t = 0 if time_step is None else time_step
                if attn_mask is not None:
                    # mask applies over cache positions: [B, H|1, S, S_max]
                    attn, new_cache = apply(
                        self._cached_attn, q, k, v, caches[li], t, attn_mask,
                        name="fused_cached_attention")
                else:
                    attn, new_cache = apply(
                        self._cached_attn, q, k, v, caches[li], t,
                        name="fused_cached_attention")
                # reference CacheKV is written in place by the fused op;
                # mirror that for eager callers while also returning the
                # updated caches for functional (traced) use
                caches[li]._data = new_cache._data
                new_caches.append(new_cache)
            else:
                attn = flash_attention(q, k, v, attn_mask=attn_mask,
                                       is_causal=attn_mask is None)
            x = x + self.dropout(self._proj(li, "out", attn.reshape([B, S, -1])))
            h = blk["ln2"](x)
            x = x + self.dropout(
                self._proj(li, "ffn2", act(self._proj(li, "ffn1", h))))
        if caches is not None:
            return x, new_caches
        return x


class FusedMultiTransformerInt8(FusedMultiTransformer):
    """Int8 stacked transformer (reference:
    fused_multi_transformer_int8_op.cu + attn_gemm_int8.h — per-layer
    int8 GEMMs with dequant rescale; inference-only, like the reference op).

    TPU-native quantization recipe:
    - weights are stored int8 with per-output-channel fp32 scales
      (halves/quarters weight HBM, the dominant decode-time traffic),
    - act_quant="dynamic" (default) also quantizes activations per tensor
      at runtime and runs int8 x int8 -> int32 dot_general — the MXU has a
      native int8 path — then dequantizes by act_scale * w_scale,
    - act_quant="none" is weight-only: dequantize weights into the
      activation dtype on the fly (robust to outlier activations).

    Build one with `FusedMultiTransformerInt8.from_float(fmt)` to quantize
    an existing FusedMultiTransformer, or construct directly and call
    load-state on the float twin before `quantize_()`.
    """

    def _fused_decode_ok(self, x, cache):
        # the float fused-decode kernel would bypass the int8 GEMM
        # reroute (and with free_float=False silently use the stale float
        # weights) — quantized decode keeps its own path
        return False

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5, nranks=1, ring_id=-1,
                 act_quant="dynamic", name=None):
        super().__init__(embed_dim, num_heads, dim_feedforward,
                         dropout_rate, activation, normalize_before,
                         num_layers, epsilon, nranks, ring_id, name)
        if act_quant not in ("dynamic", "none"):
            raise ValueError("act_quant must be 'dynamic' or 'none'")
        self.act_quant = act_quant
        self._qweights = None   # [{name: (int8 w, f32 scale)}] per layer

    _QNAMES = ("qkv", "out", "ffn1", "ffn2")

    def quantize_(self, free_float=True):
        """Quantize the current float weights (per-out-channel symmetric
        int8, reference round-to-nearest with 127 bound). free_float=True
        (default) releases the float weight buffers so the advertised
        weight-HBM saving is real; state_dict() then materializes
        dequantized weights on demand."""
        qw = []
        for blk in self.layers:
            entry = {}
            for nm in self._QNAMES:
                w = blk[nm].weight._data.astype(jnp.float32)   # [in, out]
                scale = jnp.max(jnp.abs(w), axis=0) / 127.0
                scale = jnp.maximum(scale, 1e-8)
                wi8 = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
                entry[nm] = (wi8, scale, tuple(w.shape), blk[nm].weight.dtype)
                if free_float:
                    blk[nm].weight._data = jnp.zeros((), blk[nm].weight.dtype)
            qw.append(entry)
        self._qweights = qw
        return self

    def state_dict(self, *a, **k):
        """Materialize dequantized weights for the freed float params so
        checkpoints of a quantized module stay loadable by the float
        twin (values carry the quantization error, as expected). The
        entries are FRESH tensors — the module's own freed buffers stay
        freed."""
        from ...core.tensor import Tensor as _T

        out = super().state_dict(*a, **k)
        if self._qweights is None:
            return out
        freed = {}
        for blk, entry in zip(self.layers, self._qweights):
            for nm, (wi8, scale, shape, dt) in entry.items():
                freed[id(blk[nm].weight)] = (wi8, scale, dt)
        for key, t in list(out.items()):
            hit = freed.get(id(t))
            if hit is not None:
                wi8, scale, dt = hit
                out[key] = _T((wi8.astype(jnp.float32) * scale).astype(dt))
        return out

    @classmethod
    def from_float(cls, fmt: "FusedMultiTransformer", act_quant="dynamic"):
        embed = fmt.num_heads * fmt.head_dim
        ffn = fmt.layers[0]["ffn1"].weight.shape[1]
        q = cls(embed, fmt.num_heads, ffn, dropout_rate=fmt.dropout_rate,
                activation=fmt.activation, num_layers=fmt.num_layers,
                epsilon=fmt.epsilon, act_quant=act_quant)
        q.set_state_dict(fmt.state_dict())
        return q.quantize_()

    def _proj(self, li, nm, x):
        """Reroute the parent's four heavy matmuls through int8."""
        if self._qweights is None:
            raise RuntimeError(
                "FusedMultiTransformerInt8 weights are not quantized yet — "
                "call quantize_() (or build via from_float)")
        return self._q_linear(x, li, nm)

    def _q_linear(self, x, li, nm):
        """x @ W through the int8 path (+ float bias)."""
        from ...core.dispatch import apply

        wi8, scale = self._qweights[li][nm][:2]
        bias = self.layers[li][nm].bias
        dynamic = self.act_quant == "dynamic"

        def fn(a, w, s, *maybe_b):
            import jax

            if dynamic:
                amax = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
                s_a = (amax / 127.0).astype(jnp.float32)
                ai8 = jnp.clip(jnp.round(a / s_a.astype(a.dtype)),
                               -127, 127).astype(jnp.int8)
                acc = jax.lax.dot_general(
                    ai8, w, (((a.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out = acc.astype(jnp.float32) * (s_a * s)
            else:
                out = a @ (w.astype(a.dtype) * s.astype(a.dtype))
            out = out.astype(a.dtype)
            if maybe_b:
                out = out + maybe_b[0]
            return out

        args = [x, wi8, scale]
        if bias is not None:
            args.append(bias)
        return apply(fn, *args, name=f"int8_{nm}")



def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    """Expert-choice MoE (reference: incubate/nn/functional/fused_ec_moe
    over fused_moe_kernel.cu): each expert selects its top seq_len/16
    tokens by gate logit, applies its FFN as one batched einsum over the
    expert dim (MXU-friendly — no host-side grouping), and the outputs
    scatter back weighted by the softmax gate probability, residual-added.

    x [B,S,D]; gate [B,S,E]; bmm0_weight [E,D,F]; bmm0_bias [E,1,F];
    bmm1_weight [E,F,D]; bmm1_bias [E,1,D].
    """
    from ...core.dispatch import apply
    import jax

    if act_type not in ("gelu", "relu"):
        raise ValueError("act_type must be 'gelu' or 'relu'")

    def fn(xa, g, w0, b0, w1, b1):
        B, S, D = xa.shape
        E = g.shape[-1]
        cap = max(S // 16, 1)           # reference capacity rule
        probs = jax.nn.softmax(g, axis=-1)            # [B,S,E]
        logits_e = jnp.swapaxes(g, 1, 2)              # [B,E,S]
        _, idx = jax.lax.top_k(logits_e, cap)         # [B,E,cap]
        sel = jnp.take_along_axis(
            xa[:, None], idx[..., None], axis=2)      # [B,E,cap,D]
        h = jnp.einsum("becd,edf->becf", sel, w0,
                       preferred_element_type=jnp.float32).astype(xa.dtype)
        h = h + b0                # [E,1,F] broadcasts over [B,E,cap,F]
        h = jax.nn.gelu(h, approximate=True) if act_type == "gelu" \
            else jax.nn.relu(h)
        o = jnp.einsum("becf,efd->becd", h, w1,
                       preferred_element_type=jnp.float32).astype(xa.dtype)
        o = o + b1                # [E,1,D] broadcasts over [B,E,cap,D]
        p = jnp.take_along_axis(jnp.swapaxes(probs, 1, 2), idx, axis=2)
        o = o * p[..., None]                          # [B,E,cap,D]
        out = jnp.zeros_like(xa)
        b_ix = jnp.broadcast_to(jnp.arange(B)[:, None, None], idx.shape)
        out = out.at[b_ix.reshape(-1), idx.reshape(-1)].add(
            o.reshape(-1, D))
        return xa + out

    return apply(fn, x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                 bmm1_bias, name="fused_ec_moe")


class FusedEcMoe(Layer):
    """Layer form (reference: incubate/nn/layer/fused_ec_moe.py
    FusedEcMoe). forward(x, gate) -> [B, S, D]."""

    def __init__(self, hidden_size, inter_size, num_expert, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ...nn.initializer import XavierNormal, Constant

        if act_type not in ("gelu", "relu"):   # fail at construction
            raise ValueError("act_type must be 'gelu' or 'relu'")
        self.act_type = act_type
        self.bmm_weight0 = self.create_parameter(
            shape=[num_expert, hidden_size, inter_size], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bmm_bias0 = self.create_parameter(
            shape=[num_expert, 1, inter_size], attr=bias_attr,
            default_initializer=Constant(0.0))
        self.bmm_weight1 = self.create_parameter(
            shape=[num_expert, inter_size, hidden_size], attr=weight_attr,
            default_initializer=XavierNormal())
        self.bmm_bias1 = self.create_parameter(
            shape=[num_expert, 1, hidden_size], attr=bias_attr,
            default_initializer=Constant(0.0))

    def forward(self, x, gate):
        return fused_ec_moe(x, gate, self.bmm_weight0, self.bmm_bias0,
                            self.bmm_weight1, self.bmm_bias1, self.act_type)


from . import functional  # noqa: E402  (needs fused_ec_moe above)


class FusedLinear(Layer):
    """Linear whose matmul+bias ride one fused XLA kernel (reference
    incubate/nn/layer/fc.py FusedLinear over fused_gemm_epilogue)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from .functional import fused_linear

        return fused_linear(x, self.weight, self.bias,
                            transpose_weight=self._transpose)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = LayerNorm(residual + dropout(x + bias)) in one fused region
    (reference incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm

        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Encoder layer over the fused attention + FFN ops (reference
    incubate/nn/layer/fused_transformer.py FusedTransformerEncoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        # None defaults to dropout_rate (reference fused_transformer.py)
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedTransformerEncoderLayer incremental cache is not "
                "wired; use FusedMultiTransformer's CacheKV decode path "
                "(gen_cache + time_step) for autoregressive decoding")
        return self.ffn(self.attn(src, attn_mask=src_mask))


__all__ += ["FusedLinear", "FusedBiasDropoutResidualLayerNorm",
            "FusedTransformerEncoderLayer"]
