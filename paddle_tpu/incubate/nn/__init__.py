"""incubate.nn fused layers (reference: python/paddle/incubate/nn/ —
FusedMultiHeadAttention, FusedFeedForward backed by fused_attention_op.cu /
fused_feedforward_op.cu). TPU-native: flash attention (Pallas) + XLA-fused
FFN."""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn.common import Linear, Dropout
from ...nn.norm import LayerNorm
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward"]


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon)
        self.post_ln = LayerNorm(embed_dim, epsilon)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...ops.manipulation import reshape, split

        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        b, s, _ = x.shape
        qkv = self.qkv(x)
        q, k, v = split(qkv, 3, axis=-1)
        q = reshape(q, [b, s, self.num_heads, self.head_dim])
        k = reshape(k, [b, s, self.num_heads, self.head_dim])
        v = reshape(v, [b, s, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training,
        )
        out = reshape(out, [b, s, self.embed_dim])
        out = self.dropout(self.out_proj(out))
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr, linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.activation = activation

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.linear2(self.dropout1(getattr(F, self.activation)(self.linear1(x))))
        x = residual + self.dropout2(x)
        if not self.normalize_before:
            x = self.ln(x)
        return x
