"""Quantization framework (reference: python/paddle/quantization/ —
QuantConfig (config.py), QAT (qat.py:22), PTQ (ptq.py), quanters
(quanters/abs_max.py FakeQuanterWithAbsMaxObserver), observers; legacy
imperative QAT at python/paddle/fluid/contrib/slim).

TPU-native notes: fake-quant is expressed with a straight-through
estimator built from plain ops (round + STE via stop-gradient), so QAT
trains inside the same whole-graph jit as everything else; int8 inference
folds scales into the weights (XLA int8 matmuls feed the MXU directly).
"""
from __future__ import annotations

import copy
import warnings

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer import Layer
from ..nn.common import Linear
from ..nn.conv import Conv2D
from .. import nn as _nn
import paddle_tpu.nn.functional as F

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "FakeQuanterWithAbsMaxObserver", "WeightAbsMaxQuanter", "AbsmaxObserver",
    "PassthroughWeightObserver", "QuantedLinear", "QuantedConv2D",
    "quantize_linear", "dequantize_linear",
]


# ---------------------------------------------------------------------------
# low-level fake-quant ops
# ---------------------------------------------------------------------------
def _qdq_fn(a, s, qmax):
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fake_quant_ste(x, scale, bit_length=8):
    """Quantize-dequantize with straight-through gradient:
    y = x + stop_grad(qdq(x) - x)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(a, s):
        dq = _qdq_fn(a, s, qmax)
        # straight-through: forward dq, backward identity wrt a
        return a + jax.lax.stop_gradient(dq - a)

    return apply(fn, x, scale, name="fake_quant")


def _qdq(x, scale, bit_length=8):
    """Grad-free quantize-dequantize for pure-inference wrappers: the same
    forward values as `_fake_quant_ste`, without dragging the STE's
    identity-gradient machinery (an extra sub/add + stop_gradient node)
    into models that will never be differentiated."""
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(lambda a, s: _qdq_fn(a, s, qmax), x, scale, name="qdq")


def quantize_linear(x, scale, zero_point=0, bit_length=8, name=None):
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(
        lambda a, s: jnp.clip(jnp.round(a / jnp.maximum(s, 1e-9) * qmax) + zero_point,
                              -qmax - 1, qmax).astype(jnp.int8),
        x, scale, name="quantize_linear")


def dequantize_linear(x, scale, zero_point=0, bit_length=8, name=None):
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(
        lambda a, s: (a.astype(jnp.float32) - zero_point) * s / qmax,
        x, scale, name="dequantize_linear")


# ---------------------------------------------------------------------------
# quanters / observers
# ---------------------------------------------------------------------------
class BaseQuanter(Layer):
    bit_length = 8

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return Tensor(jnp.zeros_like(self.scales()._data))


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT activation quanter: EMA of abs-max as scale + STE fake quant
    (reference: quanters/abs_max.py, moving_rate default 0.9)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("_scale", Tensor(jnp.ones((), jnp.float32)))
        self.register_buffer("_state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        if self.training:
            # pure-jnp buffer update (same pattern as batch_norm running
            # stats): traces cleanly under whole-graph jit, no host sync
            m = self.moving_rate
            cur = jnp.maximum(jnp.max(jnp.abs(x._data)).astype(jnp.float32), 1e-9)
            prev = self._scale._data
            first = self._state._data < 0.5
            self._scale._data = jnp.where(first, cur, m * prev + (1 - m) * cur)
            self._state._data = self._state._data + 1
        return _fake_quant_ste(x, self._scale, self.bit_length)

    def scales(self):
        return self._scale


class WeightAbsMaxQuanter(BaseQuanter):
    """Per-tensor abs-max weight quanter (recomputed each forward from the
    live weight — weights change every optimizer step under QAT)."""

    def __init__(self, bit_length=8, name=None):
        super().__init__()
        self.bit_length = bit_length
        self.register_buffer("_scale", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, w):
        scale = apply(lambda a: jnp.maximum(jnp.max(jnp.abs(a)), 1e-9), w,
                      name="abs_max")
        self._scale._data = jax_stop(scale._data)
        return _fake_quant_ste(w, scale, self.bit_length)

    def scales(self):
        return self._scale


def jax_stop(a):
    return jax.lax.stop_gradient(a)


class PassthroughWeightObserver(BaseQuanter):
    """PTQ weight observer: records abs-max but leaves the weight
    untouched during calibration (quantization happens at convert)."""

    def __init__(self, bit_length=8):
        super().__init__()
        self.bit_length = bit_length
        self.register_buffer("_scale", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, w):
        # pure-jnp device-side update (no np.asarray round-trip: that was
        # a device→host sync per calibration batch, and a tracer error
        # under jit) — same buffer-update pattern as the QAT quanter
        self._scale._data = jnp.maximum(
            jnp.max(jnp.abs(w._data)).astype(jnp.float32), 1e-9)
        return w

    def scales(self):
        return self._scale


class AbsmaxObserver(BaseQuanter):
    """PTQ observer: running abs-max over calibration batches (reference:
    observers/abs_max.py)."""

    def __init__(self, quant_bits=8, name=None):
        super().__init__()
        self.bit_length = quant_bits
        self.register_buffer("_max", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        # device-side running max — the old np.asarray(...) round-trip
        # forced a host sync on every calibration batch and broke under
        # a traced forward
        self._max._data = jnp.maximum(
            self._max._data,
            jnp.max(jnp.abs(x._data)).astype(jnp.float32))
        return x  # observers pass activations through unchanged

    def scales(self):
        return self._max


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
class _SingleConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Maps layers to (activation quanter factory, weight quanter factory)
    (reference: quantization/config.py — default + per-type + per-layer)."""

    def __init__(self, activation=None, weight=None):
        self._default = _SingleConfig(activation, weight)
        self._type_configs = {}
        self._layer_configs = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = _SingleConfig(activation, weight)

    def add_layer_config(self, layers, activation=None, weight=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        for l in layers:
            self._layer_configs[id(l)] = _SingleConfig(activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return self._default


def _make(factory):
    if factory is None:
        return None
    return factory() if callable(factory) and not isinstance(factory, Layer) else factory


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------
class QuantedLinear(Layer):
    def __init__(self, layer: Linear, act_quanter=None, weight_quanter=None):
        super().__init__()
        self.inner = layer
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter or WeightAbsMaxQuanter()

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight_quanter(self.inner.weight)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer: Conv2D, act_quanter=None, weight_quanter=None):
        super().__init__()
        self.inner = layer
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter or WeightAbsMaxQuanter()

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight_quanter(self.inner.weight)
        l = self.inner
        return F.conv2d(x, w, l.bias, stride=l._stride, padding=l._padding,
                        dilation=l._dilation, groups=l._groups)


_QUANTABLE = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _swap_layers(model, make_wrapper):
    for name, sub in list(model._sub_layers.items()):
        wrapped = make_wrapper(sub)
        if wrapped is not None:
            # setattr keeps Layer.__setattr__'s __dict__ mirror in sync —
            # a bare _sub_layers[name] write leaves `self.<name>` (the
            # attribute most forwards actually call) pointing at the
            # UNWRAPPED layer
            setattr(model, name, wrapped)
        else:
            _swap_layers(sub, make_wrapper)
    return model


# ---------------------------------------------------------------------------
# QAT / PTQ drivers
# ---------------------------------------------------------------------------
def _to_weight_only(layer, weight_dtype, per_channel):
    """Materialize a QuantedLinear's inner Linear as a real low-bit
    `lowbit.WeightOnlyLinear`, flowing the weight quanter/observer's
    calibrated abs-max through as the quantization scale (per-tensor,
    matching the fake-quant training numerics) unless `per_channel`
    re-derives per-output-channel scales from the raw weight."""
    from ..lowbit.weight_only import WeightOnlyLinear
    from ..ops.lowbit import qmax_for_bits

    inner = layer.inner
    scale = None
    if not per_channel:
        bits = {"int8": 8, "int4": 4}[weight_dtype]
        absmax = jnp.maximum(
            jnp.max(jnp.abs(inner.weight._data)).astype(jnp.float32), 1e-9)
        wq = layer.weight_quanter
        if wq is not None and float(wq.scales()._data) > 0:
            absmax = wq.scales()._data.astype(jnp.float32)
        scale = absmax / qmax_for_bits(bits)
    return WeightOnlyLinear.from_linear(
        inner, weight_dtype=weight_dtype, per_channel=per_channel,
        scale=scale)


class QAT:
    """Quantization-aware training: swap quantable layers for fake-quant
    wrappers (reference: quantization/qat.py:22)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def wrapper(layer):
            for base, qcls in _QUANTABLE.items():
                if isinstance(layer, base):
                    cfg = self.config._config_for(layer)
                    act = _make(cfg.activation)
                    wq = _make(cfg.weight) or WeightAbsMaxQuanter()
                    return qcls(layer, act, wq)
            return None

        return _swap_layers(model, wrapper)

    def convert(self, model: Layer, inplace=False, weight_only=None,
                per_channel=False):
        """Fold fake quant into static scales for inference: weights are
        quantize-dequantized once with the final scales, activation
        quanters become fixed-scale qdq.

        weight_only="int8"|"int4" targets the REAL low-bit runtime
        instead: QuantedLinear becomes `lowbit.WeightOnlyLinear` (packed
        codes + scales, actually smaller) with the trained quanter scale
        flowing through; the calibrated activation qdq wrapper is kept.
        QuantedConv2D stays on the qdq-fold path (weight-only packing is
        a Linear-shaped optimization).
        """
        if not inplace:
            model = copy.deepcopy(model)

        def fold(layer):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                if weight_only is not None and isinstance(layer,
                                                          QuantedLinear):
                    inner = _to_weight_only(layer, weight_only, per_channel)
                else:
                    inner = layer.inner
                    w = layer.weight_quanter(inner.weight)
                    inner.weight._data = jax_stop(w._data)
                # the learned activation scale becomes a fixed-scale qdq
                aq = layer.activation_quanter
                if aq is not None and float(aq.scales()._data) > 0:
                    return _FixedQDQ(inner, Tensor(aq.scales()._data),
                                     aq.bit_length)
                return inner
            return None

        return _swap_layers(model, fold)


class PTQ:
    """Post-training quantization: insert observers, calibrate with
    forward passes, convert to fixed-scale qdq (reference: ptq.py)."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig(
            activation=AbsmaxObserver, weight=None)

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def wrapper(layer):
            for base, qcls in _QUANTABLE.items():
                if isinstance(layer, base):
                    cfg = self.config._config_for(layer)
                    act = _make(cfg.activation) or AbsmaxObserver()
                    return qcls(layer, act, PassthroughWeightObserver())
            return None

        model = _swap_layers(model, wrapper)
        model.eval()
        return model

    def convert(self, model: Layer, inplace=False, weight_only=None,
                per_channel=False):
        """weight_only="int8"|"int4": target `lowbit.WeightOnlyLinear`
        with the observer-calibrated scales (see QAT.convert)."""
        if not inplace:
            model = copy.deepcopy(model)

        def fold(layer):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                if weight_only is not None and isinstance(layer,
                                                          QuantedLinear):
                    inner = _to_weight_only(layer, weight_only, per_channel)
                else:
                    inner = layer.inner
                    # quantize-dequantize the weight once with the final
                    # scale
                    w = WeightAbsMaxQuanter(layer.weight_quanter.bit_length)(
                        inner.weight)
                    inner.weight._data = jax_stop(w._data)
                obs = layer.activation_quanter
                if isinstance(obs, AbsmaxObserver) and float(obs.scales()._data) > 0:
                    scale = Tensor(obs.scales()._data)
                    bits = obs.bit_length
                    return _FixedQDQ(inner, scale, bits)
                return inner
            return None

        return _swap_layers(model, fold)


class _FixedQDQ(Layer):
    """Inference wrapper: fixed-scale activation qdq before the layer."""

    def __init__(self, inner, scale, bits):
        super().__init__()
        self.inner = inner
        self.register_buffer("_scale", scale)
        self._bits = bits

    def forward(self, x):
        # grad-free qdq: identical forward numerics to _fake_quant_ste,
        # no STE gradient plumbing in inference graphs
        return self.inner(_qdq(x, self._scale, self._bits))


def quanter(class_name):
    """Factory-declaration decorator (reference quantization/factory.py:73
    @quanter): registers `class_name` in paddle_tpu.quantization as a
    factory whose instances carry the constructor args and materialize the
    decorated quanter layer via _instance(layer)."""

    def wrapper(target_class):
        class _Factory:
            def __init__(self, *args, **kwargs):
                self._args = args
                self._kwargs = kwargs

            def _get_class(self):
                return target_class

            def _instance(self, layer=None):
                if layer is not None:
                    return target_class(layer, *self._args, **self._kwargs)
                return target_class(*self._args, **self._kwargs)

        _Factory.__name__ = class_name
        import sys

        setattr(sys.modules[__name__], class_name, _Factory)
        if class_name not in __all__:
            __all__.append(class_name)
        return target_class

    return wrapper


__all__ += ["quanter"]
