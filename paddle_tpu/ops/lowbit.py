"""Low-bit storage/compute primitives (array level) — the op layer under
`paddle_tpu.lowbit` (EQuARX + low-bit KV serving, PAPERS.md: int8 is the
"free" compression point on TPU — MXU-native matmuls, halved HBM/ICI
bytes, negligible accuracy loss with abs-max scaling).

Conventions (all functions are jnp-level, jit-safe, no Tensor wrapper):

- **symmetric abs-max quantization**: ``q = clip(round(x / scale), -qmax,
  qmax)`` with ``scale = absmax / qmax`` so ``dequant(q) = q * scale``.
  (Note this differs from `paddle_tpu.quantization`'s fake-quant, which
  keeps ``scale = absmax`` and divides by qmax at use — the lowbit layout
  stores the *ready-to-multiply* scale because the scale tensor is
  persistent runtime state, not a trace-transient.)
- **int4 packing**: two 4-bit codes per int8 byte along the FIRST axis
  (the reduction axis of a [in, out] weight), low nibble = even row.
  Odd first dims are zero-padded; the unpack takes the true row count.
- **fp32 accumulation**: `quantized_matmul_arrays` contracts in float32
  (`preferred_element_type`) and applies the per-out-channel scale AFTER
  the contraction — algebraically identical to dequantize-then-matmul
  (scale is constant along the contraction), one multiply per output
  instead of one per weight.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import monitor

__all__ = [
    "qmax_for_bits", "quantize_absmax_arrays", "quantize_with_scale_arrays",
    "dequantize_arrays", "pack_int4_arrays", "unpack_int4_arrays",
    "quantized_matmul_arrays",
]


def qmax_for_bits(bits: int) -> int:
    if bits not in (4, 8):
        raise ValueError(f"lowbit supports 4- and 8-bit codes, got {bits}")
    return 2 ** (bits - 1) - 1


def _count(name, **labels):
    """Per-trace telemetry (shape metadata only — safe on tracers).
    `name` is a full `subsystem/metric` literal at every call site
    (tools/lint_metrics.py checks those; this helper is the one
    documented dynamic registration)."""
    if monitor.enabled():
        c = monitor.counter(name)   # ptpu-check[metric-hygiene]: literal at call sites
        (c.labels(**labels) if labels else c).inc()


def quantize_with_scale_arrays(x, scale, qmax):
    """``clip(round(x / scale), ±qmax)`` as int8 codes, with the shared
    zero-scale guard: scale 0 (an all-zero input) yields all-zero codes,
    so dequant is an exact 0 and callers only ever MULTIPLY by the stored
    scale.  Single source of truth for the rounding convention — every
    wing (weights, KV pool, collectives) quantizes through here."""
    x = jnp.asarray(x)
    scale = jnp.asarray(scale, jnp.float32)
    q = jnp.where(scale > 0, jnp.round(x / jnp.where(scale > 0, scale, 1.0)),
                  0.0)
    return jnp.clip(q, -qmax, qmax).astype(jnp.int8)


def quantize_absmax_arrays(x, bits=8, axis=None):
    """Symmetric abs-max quantization → (codes int8, scale float32).

    axis: reduction axis/axes of the abs-max — e.g. axis=0 on an
    [in, out] weight gives one scale per OUTPUT channel (shape [out]).
    axis=None → one scalar scale (per-tensor).
    Zero inputs get scale 0 and all-zero codes (dequant is exact 0).
    """
    qmax = qmax_for_bits(bits)
    x = jnp.asarray(x)
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = amax.astype(jnp.float32) / qmax
    q = quantize_with_scale_arrays(x, scale, qmax)
    if axis is not None:
        scale = jnp.squeeze(scale, axis=axis)
    return q, scale


def dequantize_arrays(q, scale, axis=None):
    """``q * scale`` in float32.  `axis`: the axis the per-channel scale
    runs along (so it broadcasts against q); None = scalar/pre-broadcast
    scale."""
    _count("lowbit/dequant_calls", site="dequantize")
    q = jnp.asarray(q).astype(jnp.float32)
    scale = jnp.asarray(scale, jnp.float32)
    if axis is not None and scale.ndim:
        shape = [1] * q.ndim
        shape[axis] = scale.shape[0]
        scale = scale.reshape(shape)
    return q * scale


def pack_int4_arrays(q):
    """Pack int8 codes in [-7, 7] two-per-byte along axis 0.

    q: [n, ...] int8.  Returns uint8 [ceil(n/2), ...]: low nibble = row
    2i, high nibble = row 2i+1 (two's-complement nibbles).  Odd n is
    zero-padded — pass the true n to `unpack_int4_arrays`.
    """
    q = jnp.asarray(q, jnp.int8)
    n = q.shape[0]
    if n % 2:
        pad = [(0, 1)] + [(0, 0)] * (q.ndim - 1)
        q = jnp.pad(q, pad)
    u = q.astype(jnp.uint8) & 0xF
    return u[0::2] | (u[1::2] << 4)


def unpack_int4_arrays(packed, rows):
    """Inverse of `pack_int4_arrays`: uint8 [ceil(rows/2), ...] → int8
    [rows, ...] with nibble sign-extension."""
    packed = jnp.asarray(packed, jnp.uint8)
    lo = (packed & 0xF).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    # sign-extend 4-bit two's complement
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    inter = jnp.stack([lo, hi], axis=1)             # [n2, 2, ...]
    out = inter.reshape((-1,) + tuple(packed.shape[1:]))
    return out[:rows]


def quantized_matmul_arrays(x, qweight, scale, bits=8, in_features=None):
    """``x @ dequant(qweight)`` with in-kernel dequant and fp32 accumulate.

    x:        [..., in] activations (any float dtype; contraction runs in
              float32 via preferred_element_type).
    qweight:  int8 [in, out] codes, or packed uint8 [ceil(in/2), out] when
              bits=4 (pass `in_features`).
    scale:    float32 [out] per-output-channel (or scalar per-tensor) —
              applied AFTER the contraction: (x @ q) * scale ==
              x @ (q * scale) exactly in real arithmetic because scale is
              constant along the contracted axis.
    Returns [..., out] in x's dtype.
    """
    _count("lowbit/dequant_calls", site="matmul")
    x = jnp.asarray(x)
    if bits == 4:
        rows = int(in_features if in_features is not None else x.shape[-1])
        q = unpack_int4_arrays(qweight, rows)
    elif bits == 8:
        q = jnp.asarray(qweight, jnp.int8)
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    acc = jnp.matmul(x, q.astype(x.dtype),
                     preferred_element_type=jnp.float32)
    out = acc * jnp.asarray(scale, jnp.float32)
    return out.astype(x.dtype)


def quantized_bytes(shape, bits, scale_elems):
    """Storage bytes of a quantized tensor: packed codes + f32 scales."""
    n = int(np.prod(shape))
    code_bytes = n if bits == 8 else (n + 1) // 2
    return code_bytes + 4 * int(scale_elems)
