"""Paged-KV-cache attention (array level) — the serving-side primitive of
`paddle_tpu.serving` (Ragged Paged Attention, PAPERS.md: block-paged KV
caches + ragged batch decoding are the TPU-side key to high-throughput LLM
serving).

Layout: K/V live in fixed-size physical blocks

    k_blocks, v_blocks : [num_blocks, block_size, num_heads, head_dim]

and each sequence owns a *block table* row mapping its logical blocks to
physical ones.  Token `p` of a sequence lives at physical slot
``table[p // block_size] * block_size + p % block_size``.

Numerics contract: `paged_attention_arrays` reproduces the masked-softmax
decode path of `cached_attention_arrays` (models/gpt.py:326 is the
numerical reference) EXACTLY — same einsum contraction (fp32
accumulation), same additive -1e30 causal mask, same softmax and
probs-cast — so paged decode is token-for-token identical to the dense
`[B, S_max]` ring decode: gathered block rows land at the same logical
key positions, and padding rows beyond a row's context are masked to an
exact 0 probability (exp underflows to 0.0), contributing exactly nothing
to the reductions.  tests/test_serving.py pins this parity against
`GPTModel.generate()`.

No Pallas kernel here yet: at S_q = 1 the op is bandwidth-bound (MXU
irrelevant), matching the dense decode path's design note; a fused
gather+attention kernel is the obvious follow-up once serving shapes are
profiled on chip.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention_arrays", "paged_cache_update_arrays",
           "paged_gather_kv_arrays", "slot_mapping",
           "quantized_cache_update_arrays", "quantized_gather_kv_arrays"]

_NEG_INF = -1e30


def slot_mapping(block_table, positions, block_size, num_slots,
                 valid=None):
    """Physical slot of each (row, position): ``[B, S]`` int32.

    block_table: [B, max_blocks] int32 physical block ids (rows may be
    padded arbitrarily past the blocks a sequence owns — positions only
    index into the table through ``positions // block_size``).
    positions:   [B, S] int32 absolute token positions.
    valid:       optional [B, S] bool; invalid entries map to `num_slots`
    (one past the last slot) so a scatter with mode='drop' discards them.
    """
    block_table = jnp.asarray(block_table, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    bs = int(block_size)
    logical = positions // bs
    maxb = block_table.shape[1]
    phys = jnp.take_along_axis(
        block_table, jnp.clip(logical, 0, maxb - 1), axis=1)
    slots = phys * bs + positions % bs
    if valid is not None:
        slots = jnp.where(valid, slots, jnp.int32(num_slots))
    return slots


def paged_cache_update_arrays(blocks, rows, slots):
    """Scatter new K (or V) rows into the paged pool.

    blocks: [num_blocks, block_size, H, D] (or [.., H*D])
    rows:   [B, S, H, D] (or [B, S, H*D]) new keys/values
    slots:  [B, S] int32 physical slots (from `slot_mapping`); out-of-range
            entries (padding / inactive rows) are DROPPED, never clamped —
            a clamp would silently corrupt the last block.
    Returns the updated pool (same shape/dtype as `blocks`).
    """
    nb, bs = blocks.shape[0], blocks.shape[1]
    feat = blocks.shape[2:]
    flat = blocks.reshape((nb * bs,) + tuple(feat))
    r = rows.reshape((-1,) + tuple(feat)).astype(blocks.dtype)
    flat = flat.at[slots.reshape(-1)].set(r, mode="drop")
    return flat.reshape(blocks.shape)


def paged_gather_kv_arrays(blocks, block_table):
    """Gather one sequence-major view of the pool: [B, max_blocks *
    block_size, H, D].  Rows past a sequence's context hold garbage (stale
    or zero blocks) — callers mask them; table entries are clipped into
    range (padding entries gather *some* block, masked the same way)."""
    nb, bs = blocks.shape[0], blocks.shape[1]
    feat = blocks.shape[2:]
    tbl = jnp.clip(jnp.asarray(block_table, jnp.int32), 0, nb - 1)
    g = jnp.take(blocks, tbl, axis=0)          # [B, maxb, bs, *feat]
    b, maxb = tbl.shape
    return g.reshape((b, maxb * bs) + tuple(feat))


def quantized_cache_update_arrays(blocks, scales, rows, slots, qmax=127):
    """Scatter new K (or V) rows into an int8 paged pool with
    per-block-per-head abs-max scales (the `lowbit` KV wing).

    blocks: int8 [num_blocks, block_size, H, D] codes
    scales: f32  [num_blocks, H] — ``value = code * scale``
    rows:   [B, S, H, D] float K/V rows to write
    slots:  [B, S] int32 physical slots; out-of-range (padding) entries
            are dropped exactly like `paged_cache_update_arrays`.

    A block's scale only ever GROWS (amax of everything written since the
    block was taken — the allocator resets scales on reallocation).  When
    an incoming row raises a block's amax, that block's existing codes
    are rescaled ``round(q · old/new)`` — one extra rounding, bounded by
    half an int8 step at the new scale.  When the scale is unchanged the
    rescale factor is exactly 1.0 and the codes pass through bit-stable
    (int8→f32→round is exact), which is what keeps steady-state decode
    deterministic.

    Returns (blocks', scales').
    """
    nb, bs = blocks.shape[0], blocks.shape[1]
    h = blocks.shape[2]
    flat_slots = jnp.asarray(slots, jnp.int32).reshape(-1)
    block_ids = flat_slots // bs                     # invalid slots → nb
    rows_flat = rows.reshape(-1, h, blocks.shape[3])
    # per-(block, head) abs-max of the incoming rows; the extra row nb
    # swallows padding/invalid writes and is sliced off
    row_amax = jnp.max(jnp.abs(rows_flat.astype(jnp.float32)), axis=-1)
    cand = jnp.zeros((nb + 1, h), jnp.float32).at[
        jnp.clip(block_ids, 0, nb)].max(row_amax)[:nb]
    new_scales = jnp.maximum(scales, cand / qmax)
    factor = jnp.where(new_scales > 0, scales / jnp.where(
        new_scales > 0, new_scales, 1.0), 1.0)
    # rescale ONLY the written blocks (the only ones whose scale can have
    # changed): gather → rescale → scatter back at block granularity.
    # Keeps the update O(written tokens), not O(pool) — the fp path's
    # scatter shape — so XLA mutates the donated pool in place.
    # Duplicate ids (a prefill chunk filling one block) scatter identical
    # values; invalid ids (nb) gather clipped garbage that the
    # mode="drop" scatter discards.
    gid = jnp.clip(block_ids, 0, nb - 1)
    gfactor = factor[gid]                            # [N, H]
    rescaled = jnp.clip(
        jnp.round(blocks[gid].astype(jnp.float32)
                  * gfactor[:, None, :, None]),
        -qmax, qmax).astype(jnp.int8)                # [N, bs, H, D]
    q = blocks.at[block_ids].set(rescaled, mode="drop")
    # quantize the incoming rows against their block's (new) scale
    wsc = jnp.concatenate([new_scales,
                           jnp.ones((1, h), jnp.float32)], axis=0)[
        jnp.clip(block_ids, 0, nb)]                  # [(B*S), H]
    wsc = jnp.where(wsc > 0, wsc, 1.0)[:, :, None]
    q_rows = jnp.clip(jnp.round(rows_flat.astype(jnp.float32) / wsc),
                      -qmax, qmax).astype(jnp.int8)
    flat = q.reshape(nb * bs, h, blocks.shape[3])
    flat = flat.at[flat_slots].set(q_rows, mode="drop")
    return flat.reshape(blocks.shape), new_scales


def quantized_gather_kv_arrays(blocks, scales, block_table):
    """Dequantizing gather: the int8 analog of `paged_gather_kv_arrays`,
    returning float32 [B, max_blocks * block_size, H, D] =
    ``codes * per-block-per-head scale``.

    This IS the separate dequant pass quantized serving pays on the
    bucketed path (a 4-byte fp32 materialization of the 1-byte pool);
    `ops.ragged_paged_attention` exists to not call it — the counter
    below is how the bench/tests pin that (ISSUE 8 acceptance: no
    ``site="paged_gather"`` increments on the ragged path)."""
    from .lowbit import _count

    _count("lowbit/dequant_calls", site="paged_gather")
    nb, bs = blocks.shape[0], blocks.shape[1]
    tbl = jnp.clip(jnp.asarray(block_table, jnp.int32), 0, nb - 1)
    g = jnp.take(blocks, tbl, axis=0)                # [B, maxb, bs, H, D]
    s = jnp.take(scales, tbl, axis=0)                # [B, maxb, H]
    deq = g.astype(jnp.float32) * s[:, :, None, :, None]
    b, maxb = tbl.shape
    return deq.reshape((b, maxb * bs) + tuple(blocks.shape[2:]))


def paged_attention_arrays(q, k_blocks, v_blocks, block_table, pos0,
                           scale=None, k_scales=None, v_scales=None):
    """Causal attention of a (ragged) batch against its paged KV cache.

    q:            [B, S, H, D] — S=1 at decode, >1 for a prefill chunk
    k_blocks/v_blocks: [num_blocks, block_size, H, D] physical pools
                  (the current chunk's K/V must already be written —
                  write-then-attend, like the dense cache path)
    block_table:  [B, max_blocks] int32 per-row logical→physical map
    pos0:         [B] int32 absolute position of each row's FIRST query
                  (== that row's context length before this chunk)
    Returns [B, S, H, D] in q's dtype.

    Each query at absolute position p attends keys with k_pos <= p —
    the same additive -1e30 mask + fp32-softmax arithmetic as
    `cached_attention_arrays`, with a per-ROW position instead of its
    scalar `t` (that is the whole ragged-batch generalization).

    k_scales/v_scales: pass the [num_blocks, H] per-block-per-head scale
    pools to read int8-quantized K/V blocks (the lowbit KV wing) — the
    gather dequantizes, the attention arithmetic is unchanged.
    """
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if k_scales is not None:
        # lowbit path: int8 pools + per-block-per-head scales dequantize
        # inside the gather; the attention arithmetic below is unchanged
        kg = quantized_gather_kv_arrays(k_blocks, k_scales, block_table)
        vg = quantized_gather_kv_arrays(v_blocks, v_scales, block_table)
    else:
        kg = paged_gather_kv_arrays(k_blocks, block_table)  # [B, S_pad, H, D]
        vg = paged_gather_kv_arrays(v_blocks, block_table)
    s_pad = kg.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kg,
                        preferred_element_type=jnp.float32) * scale
    q_pos = jnp.asarray(pos0, jnp.int32)[:, None] + jnp.arange(
        s, dtype=jnp.int32)[None, :]                       # [B, S]
    k_pos = jnp.arange(s_pad, dtype=jnp.int32)
    causal = k_pos[None, None, :] <= q_pos[:, :, None]     # [B, S, S_pad]
    logits = jnp.where(causal[:, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vg.dtype), vg)
    return out.astype(q.dtype)
