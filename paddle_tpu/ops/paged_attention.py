"""Paged-KV-cache attention (array level) — the serving-side primitive of
`paddle_tpu.serving` (Ragged Paged Attention, PAPERS.md: block-paged KV
caches + ragged batch decoding are the TPU-side key to high-throughput LLM
serving).

Layout: K/V live in fixed-size physical blocks

    k_blocks, v_blocks : [num_blocks, block_size, num_heads, head_dim]

and each sequence owns a *block table* row mapping its logical blocks to
physical ones.  Token `p` of a sequence lives at physical slot
``table[p // block_size] * block_size + p % block_size``.

Numerics contract: `paged_attention_arrays` reproduces the masked-softmax
decode path of `cached_attention_arrays` (models/gpt.py:326 is the
numerical reference) EXACTLY — same einsum contraction (fp32
accumulation), same additive -1e30 causal mask, same softmax and
probs-cast — so paged decode is token-for-token identical to the dense
`[B, S_max]` ring decode: gathered block rows land at the same logical
key positions, and padding rows beyond a row's context are masked to an
exact 0 probability (exp underflows to 0.0), contributing exactly nothing
to the reductions.  tests/test_serving.py pins this parity against
`GPTModel.generate()`.

No Pallas kernel here yet: at S_q = 1 the op is bandwidth-bound (MXU
irrelevant), matching the dense decode path's design note; a fused
gather+attention kernel is the obvious follow-up once serving shapes are
profiled on chip.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["paged_attention_arrays", "paged_cache_update_arrays",
           "paged_gather_kv_arrays", "slot_mapping"]

_NEG_INF = -1e30


def slot_mapping(block_table, positions, block_size, num_slots,
                 valid=None):
    """Physical slot of each (row, position): ``[B, S]`` int32.

    block_table: [B, max_blocks] int32 physical block ids (rows may be
    padded arbitrarily past the blocks a sequence owns — positions only
    index into the table through ``positions // block_size``).
    positions:   [B, S] int32 absolute token positions.
    valid:       optional [B, S] bool; invalid entries map to `num_slots`
    (one past the last slot) so a scatter with mode='drop' discards them.
    """
    block_table = jnp.asarray(block_table, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    bs = int(block_size)
    logical = positions // bs
    maxb = block_table.shape[1]
    phys = jnp.take_along_axis(
        block_table, jnp.clip(logical, 0, maxb - 1), axis=1)
    slots = phys * bs + positions % bs
    if valid is not None:
        slots = jnp.where(valid, slots, jnp.int32(num_slots))
    return slots


def paged_cache_update_arrays(blocks, rows, slots):
    """Scatter new K (or V) rows into the paged pool.

    blocks: [num_blocks, block_size, H, D] (or [.., H*D])
    rows:   [B, S, H, D] (or [B, S, H*D]) new keys/values
    slots:  [B, S] int32 physical slots (from `slot_mapping`); out-of-range
            entries (padding / inactive rows) are DROPPED, never clamped —
            a clamp would silently corrupt the last block.
    Returns the updated pool (same shape/dtype as `blocks`).
    """
    nb, bs = blocks.shape[0], blocks.shape[1]
    feat = blocks.shape[2:]
    flat = blocks.reshape((nb * bs,) + tuple(feat))
    r = rows.reshape((-1,) + tuple(feat)).astype(blocks.dtype)
    flat = flat.at[slots.reshape(-1)].set(r, mode="drop")
    return flat.reshape(blocks.shape)


def paged_gather_kv_arrays(blocks, block_table):
    """Gather one sequence-major view of the pool: [B, max_blocks *
    block_size, H, D].  Rows past a sequence's context hold garbage (stale
    or zero blocks) — callers mask them; table entries are clipped into
    range (padding entries gather *some* block, masked the same way)."""
    nb, bs = blocks.shape[0], blocks.shape[1]
    feat = blocks.shape[2:]
    tbl = jnp.clip(jnp.asarray(block_table, jnp.int32), 0, nb - 1)
    g = jnp.take(blocks, tbl, axis=0)          # [B, maxb, bs, *feat]
    b, maxb = tbl.shape
    return g.reshape((b, maxb * bs) + tuple(feat))


def paged_attention_arrays(q, k_blocks, v_blocks, block_table, pos0,
                           scale=None):
    """Causal attention of a (ragged) batch against its paged KV cache.

    q:            [B, S, H, D] — S=1 at decode, >1 for a prefill chunk
    k_blocks/v_blocks: [num_blocks, block_size, H, D] physical pools
                  (the current chunk's K/V must already be written —
                  write-then-attend, like the dense cache path)
    block_table:  [B, max_blocks] int32 per-row logical→physical map
    pos0:         [B] int32 absolute position of each row's FIRST query
                  (== that row's context length before this chunk)
    Returns [B, S, H, D] in q's dtype.

    Each query at absolute position p attends keys with k_pos <= p —
    the same additive -1e30 mask + fp32-softmax arithmetic as
    `cached_attention_arrays`, with a per-ROW position instead of its
    scalar `t` (that is the whole ragged-batch generalization).
    """
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kg = paged_gather_kv_arrays(k_blocks, block_table)     # [B, S_pad, H, D]
    vg = paged_gather_kv_arrays(v_blocks, block_table)
    s_pad = kg.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kg,
                        preferred_element_type=jnp.float32) * scale
    q_pos = jnp.asarray(pos0, jnp.int32)[:, None] + jnp.arange(
        s, dtype=jnp.int32)[None, :]                       # [B, S]
    k_pos = jnp.arange(s_pad, dtype=jnp.int32)
    causal = k_pos[None, None, :] <= q_pos[:, :, None]     # [B, S, S_pad]
    logits = jnp.where(causal[:, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vg.dtype), vg)
    return out.astype(q.dtype)
