"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "all", "any", "isclose", "allclose", "is_empty",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _cmp(fn, name):
    def op(x, y, name_=None):
        x, y = _t(x), _t(y)
        return Tensor(fn(x._data, y._data))

    op.__name__ = name
    return op


equal = _cmp(lambda a, b: a == b, "equal")
not_equal = _cmp(lambda a, b: a != b, "not_equal")
greater_than = _cmp(lambda a, b: a > b, "greater_than")
greater_equal = _cmp(lambda a, b: a >= b, "greater_equal")
less_than = _cmp(lambda a, b: a < b, "less_than")
less_equal = _cmp(lambda a, b: a <= b, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return Tensor(jnp.logical_not(x._data))


def bitwise_not(x, name=None):
    return Tensor(jnp.bitwise_not(x._data))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(x._data, y._data))


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.all(x._data, axis=ax, keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.any(x._data, axis=ax, keepdims=keepdim))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(
        jnp.isclose(x._data, _t(y)._data, rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(
        jnp.allclose(x._data, _t(y)._data, rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))
