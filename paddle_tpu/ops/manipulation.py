"""Shape/layout manipulation + indexing ops (reference:
python/paddle/tensor/manipulation.py, search.py)."""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..core.dtype import convert_dtype

__all__ = [
    "reshape", "transpose", "concat", "stack", "split", "chunk",
    "squeeze", "unsqueeze", "flatten", "cast", "slice",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "index_select",
    "index_sample", "take_along_axis", "put_along_axis",
    "tile", "expand", "expand_as", "broadcast_to", "repeat_interleave",
    "flip", "roll", "rot90", "moveaxis", "swapaxes",
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    "masked_select", "masked_fill", "unique", "one_hot",
    "unbind", "numel", "shard_index", "strided_slice", "as_real", "as_complex",
    "tensordot", "cross", "searchsorted", "bincount", "unfold",
]


def reshape(x, shape, name=None):
    shape = tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)
    return apply(lambda a: jnp.reshape(a, shape), x, name="reshape")


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return apply(lambda a: jnp.transpose(a, perm), x, name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x, name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x, name="swapaxes")


def concat(x, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda *xs: jnp.concatenate(xs, axis=axis), *x, name="concat")


def stack(x, axis=0, name=None):
    return apply(lambda *xs: jnp.stack(xs, axis=axis), *x, name="stack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis)
    n = x.shape[axis]
    if isinstance(num_or_sections, int):
        if n % num_or_sections != 0:
            raise ValueError(
                f"split: dim {axis} size {n} is not divisible by {num_or_sections}"
            )
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = n - builtins.sum(s for s in sizes if s >= 0)
    offsets = np.cumsum([0] + sizes[:-1])

    def fn(a):
        return tuple(
            jax.lax.slice_in_dim(a, int(o), int(o + s), axis=axis)
            for o, s in zip(offsets, sizes)
        )

    return list(apply(fn, x, name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0):
    n = x.shape[axis]

    def fn(a):
        return tuple(
            jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis), axis=axis)
            for i in range(n)
        )

    return list(apply(fn, x, name="unbind"))


def squeeze(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a_ % a.ndim for a_ in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return apply(fn, x, name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(int(a) for a in axes)

    def fn(a):
        out = a
        for ax in axes:
            out = jnp.expand_dims(out, ax)
        return out

    return apply(fn, x, name="unsqueeze")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def fn(a):
        shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, shape)

    return apply(fn, x, name="flatten")


def cast(x, dtype):
    dt = convert_dtype(dtype)
    return apply(lambda a: a.astype(dt), x, name="cast")


def slice(x, axes, starts, ends):
    def fn(a):
        out = a
        for ax, st, en in zip(axes, starts, ends):
            st = int(st) if not isinstance(st, Tensor) else int(st.item())
            en = int(en) if not isinstance(en, Tensor) else int(en.item())
            dim = a.shape[ax]
            st = builtins.max(st + dim, 0) if st < 0 else builtins.min(st, dim)
            en = builtins.max(en + dim, 0) if en < 0 else builtins.min(en, dim)
            out = jax.lax.slice_in_dim(out, st, en, axis=ax)
        return out

    return apply(fn, x, name="slice")


def strided_slice(x, axes, starts, ends, strides):
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(st, en, sd)
        return a[tuple(idx)]

    return apply(fn, x, name="strided_slice")


def _idx_arr(index):
    return index._data if isinstance(index, Tensor) else jnp.asarray(index)


def gather(x, index, axis=0, name=None):
    idx = _idx_arr(index)
    return apply(lambda a: jnp.take(a, idx, axis=axis), x, name="gather")


def gather_nd(x, index, name=None):
    idx = _idx_arr(index)

    def fn(a):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return a[comps]

    return apply(fn, x, name="gather_nd")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis)


def index_sample(x, index):
    idx = _idx_arr(index)
    return apply(
        lambda a: jnp.take_along_axis(a, idx, axis=1), x, name="index_sample"
    )


def take_along_axis(arr, indices, axis):
    idx = _idx_arr(indices)
    return apply(
        lambda a: jnp.take_along_axis(a, idx, axis=axis), arr, name="take_along_axis"
    )


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    idx = _idx_arr(indices)
    mode = {"assign": "set", "add": "add", "mul": "multiply"}[reduce]

    def fn(a, v):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        updater = getattr(jnp, "put_along_axis", None)
        # Build explicit advanced indices (works for any rank).
        comps = []
        for d in range(a.ndim):
            if d == axis % a.ndim:
                comps.append(idx)
            else:
                shape = [1] * idx.ndim
                shape[d] = idx.shape[d]
                comps.append(jnp.broadcast_to(jnp.arange(idx.shape[d]).reshape(shape), idx.shape))
        at = a.at[tuple(comps)]
        return getattr(at, mode)(v)

    if not isinstance(values, Tensor):
        values = Tensor(jnp.asarray(values))
    return apply(fn, arr, values, name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    idx = _idx_arr(index).reshape(-1)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u)
        return a.at[idx].add(u)

    return apply(fn, x, updates, name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    idx = _idx_arr(index)

    def fn(a, u):
        comps = tuple(idx[..., i] for i in range(idx.shape[-1]))
        return a.at[comps].add(u)

    return apply(fn, x, updates, name="scatter_nd_add")


def tile(x, repeat_times, name=None):
    reps = tuple(int(r) for r in repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, name="tile")


def expand(x, shape, name=None):
    shape = tuple(int(s) for s in shape)

    def fn(a):
        tgt = list(shape)
        # -1 means keep original dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))

    return apply(fn, x, name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply(
        lambda a: jnp.repeat(a, r, axis=axis), x, name="repeat_interleave"
    )


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply(lambda a: jnp.flip(a, axis=tuple(axes)), x, name="flip")


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), x, name="roll")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, name="rot90")


# -- search / sort ----------------------------------------------------------


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(a):
        out = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))

    return Tensor(fn(x._data))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(a):
        out = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(convert_dtype(dtype))

    return Tensor(fn(x._data))


def argsort(x, axis=-1, descending=False, name=None):
    a = x._data
    out = jnp.argsort(-a if descending else a, axis=axis)
    return Tensor(out.astype(jnp.int64))


def sort(x, axis=-1, descending=False, name=None):
    def fn(a):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out

    return apply(fn, x, name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    ax = axis % x.ndim

    def fn(a):
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = apply(fn, x, name="topk")
    idx = Tensor(idx._data.astype(jnp.int64))
    return vals, idx


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition)
    cond = condition._data if isinstance(condition, Tensor) else jnp.asarray(condition)
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    if not isinstance(y, Tensor):
        y = Tensor(jnp.asarray(y))
    return apply(lambda a, b: jnp.where(cond, a, b), x, y, name="where")


def nonzero(x, as_tuple=False):
    arr = np.asarray(x._data)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n[:, None]).astype(jnp.int64)) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)).astype(jnp.int64))


def masked_select(x, mask, name=None):
    # Data-dependent output shape: the mask must be concretized host-side
    # (not jittable — reference masked_select kernel has the same dynamic-
    # shape property), but the GATHER itself runs through `apply` with the
    # now-static bool mask so gradients flow (masked_select_grad analog).
    from ..core.dispatch import apply

    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    m = np.broadcast_to(m, x.shape)
    return apply(lambda a: a[m], x, name="masked_select")


def masked_fill(x, mask, value, name=None):
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    if isinstance(value, Tensor):
        return apply(
            lambda a, v: jnp.where(m, v.astype(a.dtype), a), x, value, name="masked_fill"
        )
    return apply(lambda a: jnp.where(m, value, a), x, name="masked_fill")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(x._data)
    res = np.unique(
        arr,
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r.astype(np.int64) if r.dtype == np.intp else r)) for r in res]
    return tuple(outs)


def one_hot(x, num_classes, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(idx, num_classes, dtype=jnp.float32))


def numel(x):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Vocab-shard remap (reference: shard_index_op — used by
    VocabParallelEmbedding)."""
    size = index_num // nshards

    def fn(a):
        lo, hi = shard_id * size, (shard_id + 1) * size
        in_range = (a >= lo) & (a < hi)
        return jnp.where(in_range, a - lo, ignore_value)

    return apply(fn, input, name="shard_index")


def as_real(x):
    def fn(a):
        return jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1)

    return apply(fn, x, name="as_real")


def as_complex(x):
    return apply(
        lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, name="as_complex"
    )


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y, name="tensordot")


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None

    def fn(a, b):
        if ax is None:
            # first axis with dim 3 (paddle semantics)
            axis_ = next(i for i, s in enumerate(a.shape) if s == 3)
        else:
            axis_ = ax
        return jnp.cross(a, b, axis=axis_)

    return apply(fn, x, y, name="cross")


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence._data, values._data, side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bincount(x, weights=None, minlength=0):
    w = weights._data if isinstance(weights, Tensor) else weights
    arr = np.asarray(x._data)
    length = builtins.max(minlength, int(arr.max()) + 1 if arr.size else 0)
    out = jnp.bincount(x._data, weights=w, length=length)
    return Tensor(out)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: unfold_op) — NCHW."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                patches.append(
                    a[:, :, di : di + oh * st[0] : st[0], dj : dj + ow * st[1] : st[1]]
                )
        out = jnp.stack(patches, axis=2)  # N, C, K*K, OH, OW
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply(fn, x, name="unfold")
