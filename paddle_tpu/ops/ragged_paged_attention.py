"""Ragged paged attention — ONE fixed-shape fused program for mixed-length
prefill/decode rows over the block-paged KV pools ("Ragged Paged
Attention", PAPERS.md), with the current tokens' cache update pulled into
the same program (the MPK fuse-across-boundaries lever, PAPERS.md) and —
for the int8 KV wing — the per-block-per-head dequant applied at the K/V
block loads instead of as a separate gather-dequantize pass.

This is the serving decode workhorse ISSUE 8 / ROADMAP item 1 calls for:
the round-2 bisect pinned ~2.77 ms of the 3.34 ms decode step to the
gather-blocks → masked-attention → cache-scatter triple, and the
power-of-2 batch bucketing recompiles a fresh program every time the
running-request count crosses a boundary.  Here the engine compiles ONE
program at ``[max_num_seqs, 1]`` and every batch composition runs it.

Two implementations behind one entry point, selected like
``pallas_ops._pallas_ok`` (PTPU_ATTN_DEBUG=1 counts every gate decision):

- **Pallas kernel** (TPU, or CPU under ``PTPU_PALLAS_INTERPRET=1``), the
  decode (S_q = 1) shape: one program per row streams ONLY the row's
  ``ceil(len / block_size)`` physical blocks from HBM (double-buffered
  DMA, online softmax — the XLA fallback touches all ``max_blocks``
  gathered rows), fuses the new token's quantize+scatter as a
  read-modify-write of the row's last block BEFORE the stream (pools are
  aliased in place), and dequantizes int8 blocks at load time — the int8
  codes never exist as a dequantized [B, S_pad, H, D] float tensor
  anywhere.

- **XLA array-level fallback** (any backend, any chunk width C): the
  cache update and attention of `ops.paged_attention` composed in one
  function.  The full-precision path is BITWISE the reference
  (`paged_cache_update_arrays` + `paged_attention_arrays`) — that is what
  keeps mixed continuous batches token-identical to solo dense
  ``generate()`` on the ragged engine path.  The int8 path reuses
  `quantized_cache_update_arrays` bitwise but replaces the dequantizing
  gather with a scale-FOLDED attention: it gathers int8 CODES (1 byte per
  element instead of the 4-byte fp32 dequant materialization) plus the
  tiny per-position scales, and applies ``k_scale`` to the logits and
  ``v_scale`` to the probabilities — algebraically identical because the
  scale is constant along the contracted head_dim axis, within a last-ulp
  reassociation of the dequantize-then-einsum reference (int8 KV parity
  is a documented tolerance, PR 4; all engine rows share one arithmetic
  so engine-vs-engine invariants stay bitwise).  It never calls
  `quantized_gather_kv_arrays`, so the ragged path makes no
  ``lowbit/dequant_calls{site="paged_gather"}`` increments.

Numerics contract of the fallback: same einsum contraction (fp32
accumulation), same additive -1e30 causal mask over the SAME padded
[B, max_blocks * block_size] extent, same softmax/probs-cast as
`paged_attention_arrays` — positions past a row's true length underflow
to an exact 0 probability.  The kernel's online softmax reorders the
reductions (last-ulp, like flash decode vs the dense reference); it is
gated off the CPU parity path and pinned against the fallback by
tests/test_ragged_attention.py.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

from .paged_attention import (paged_attention_arrays,
                              paged_cache_update_arrays,
                              quantized_cache_update_arrays)
from .pallas_ops import (_NEG_INF, _count_path, _decode_seg_helpers,
                         _interpret, _on_tpu)

__all__ = ["ragged_paged_attention_arrays"]

_QMAX = 127


# ---------------------------------------------------------------------------
# dispatch gate (the _pallas_ok idiom: every decision counted under
# PTPU_ATTN_DEBUG=1 so serving shapes silently dropping to the fallback
# are observable)
# ---------------------------------------------------------------------------

def _ragged_kernel_ok(q, k_blocks, c, quant) -> bool:
    """Geometry/flag gate for the fused ragged kernel.  The kernel serves
    the decode shape (C = 1) — chunked-prefill and speculative-verify
    rows (C > 1) take the fallback, which is the parity-exact program
    anyway (a multi-token kernel variant is the natural follow-up once
    the verify path earns its on-chip A/B).
    PTPU_RAGGED_KERNEL=0 hard-disables."""
    if os.environ.get("PTPU_RAGGED_KERNEL", "").lower() in ("0", "false",
                                                            "off"):
        _count_path("ragged_fallback:disabled")
        return False
    if not (_on_tpu() or _interpret()):
        _count_path("ragged_fallback:off_tpu")
        return False
    if c != 1:
        _count_path("ragged_fallback:chunk_gt_1")
        return False
    _, _, h, d = q.shape
    bs = int(k_blocks.shape[1])
    if d not in (64, 128, 256) or (h * d) % 128 != 0:
        _count_path("ragged_fallback:head_geometry")
        return False
    # block DMAs slice [block_size, H*D] slabs: the sublane dim must be a
    # tile multiple for the pool dtype ((8,128) f32 / (16,128) bf16 /
    # (32,128) int8)
    sub = 32 if quant else (16 if k_blocks.dtype == jnp.bfloat16 else 8)
    if bs % sub != 0:
        _count_path("ragged_fallback:block_size")
        return False
    if not quant and q.dtype != k_blocks.dtype:
        # the kernel's matmuls want matching operand dtypes (the XLA
        # fallback einsum promotes mixed q/pool dtypes instead)
        _count_path("ragged_fallback:dtype_mix")
        return False
    _count_path("ragged_kernel")
    return True


# ---------------------------------------------------------------------------
# the fused kernel (S_q = 1): cache update (read-modify-write of the
# row's last block) then a double-buffered streamed attention over the
# row's blocks, int8 dequant fused into the block loads
# ---------------------------------------------------------------------------

def _ragged_fused_kernel(len_ref, slot_ref, tbl_ref, q_ref, kn_ref, vn_ref,
                         k_hbm, v_hbm, *refs, bs, h, d, nb, maxb, scale,
                         quant):
    """One program per batch row r:

    1. DMA the row's TARGET block (the one its write slot lands in) into
       VMEM, splice/quantize the new token's K/V row in (int8: grow the
       block scale monotonically and rescale the existing codes exactly
       like `quantized_cache_update_arrays`), DMA it back — pools and
       scale tables are aliased in place, and blocks a row writes are
       always privately owned (the engine privatizes shared last blocks
       at fork), so programs never race.
    2. Stream the row's ``ceil(len/bs)`` blocks from HBM (double-buffered
       DMA through the row's block table in SMEM), dequantizing int8
       codes at load via the per-block-per-head scales, with an online
       softmax; the target block's contribution comes from the updated
       VMEM copy, never re-read through the alias.

    Rows whose write slot is out of range (batch padding / evicted rows)
    skip the write and produce garbage output the engine ignores.  Heads
    live flattened in the lane dim; per-head logits/weights go through
    the segment-indicator matmuls of `_decode_seg_helpers` (Mosaic's
    (8,128) tiling forbids slicing H or D when they are not tile
    multiples)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    refs = list(refs)
    if quant:
        gks_ref = refs.pop(0)
        gvs_ref = refs.pop(0)
        refs.pop(0)             # k_scales input: aliased, read pre-gathered
        refs.pop(0)             # v_scales input
        o_ref, ko_hbm, vo_hbm, kso_hbm, vso_hbm = refs[:5]
        kbuf, vbuf, sem, ublk, usem, sstage = refs[5:]
    else:
        o_ref, ko_hbm, vo_hbm = refs[:3]
        kbuf, vbuf, sem, ublk, usem = refs[3:]
    hd = h * d
    r = pl.program_id(0)
    length = jnp.maximum(len_ref[r], 0)
    slot = slot_ref[r]
    valid = (slot >= 0) & (slot < nb * bs)
    blk = jnp.clip(slot // bs, 0, nb - 1)
    off = jnp.where(valid, slot % bs, 0)
    # the write slot is the row's LAST position (length - 1), so the
    # target block is the last logical block the attention stream visits
    tkb = jnp.where(valid, jnp.clip((length - 1) // bs, 0, maxb - 1), -1)

    fast = (jnp.bfloat16 if (not quant and kbuf.dtype == jnp.bfloat16)
            else jnp.float32)
    seg, expand, seg_dot = _decode_seg_helpers(h, d, fast)

    # -- 1. fused cache update ---------------------------------------------
    rk = pltpu.make_async_copy(k_hbm.at[pl.ds(blk, 1)], ublk.at[0],
                               usem.at[0])
    rv = pltpu.make_async_copy(v_hbm.at[pl.ds(blk, 1)], ublk.at[1],
                               usem.at[1])
    rk.start()
    rv.start()
    rk.wait()
    rv.wait()
    off_mask = (jax.lax.broadcasted_iota(jnp.int32, (1, bs, 1), 1) == off)

    if quant:
        kn32 = kn_ref[...].astype(jnp.float32)          # [1, 1, hd]
        vn32 = vn_ref[...].astype(jnp.float32)
        lane_h = jax.lax.broadcasted_iota(jnp.int32, (1, h), 1)
        head_of = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hd), 2) // d

        def _head_amax(x32):
            # per-head abs-max of one [1, 1, hd] row as a lane-oriented
            # [1, h] vector (static unroll: h is small, and a lane-space
            # segmented max has no matmul form)
            res = jnp.zeros((1, h), jnp.float32)
            ax = jnp.abs(x32)
            for j in range(h):
                mj = jnp.max(jnp.where(head_of == j, ax, 0.0))
                res = jnp.where(lane_h == j, mj, res)
            return res

        def _sel_row(g_ref, kb):
            # row kb of the pre-gathered [1, maxb, h] scale view as
            # [1, h] — masked sublane sum instead of a dynamic VMEM slice
            rows = g_ref[...][0]                         # [maxb, h]
            mask = (jax.lax.broadcasted_iota(jnp.int32, (maxb, 1), 0)
                    == kb)
            return jnp.sum(jnp.where(mask, rows, 0.0), axis=0,
                           keepdims=True)

        def _quant_update(xn32, old_s, blk_codes):
            # mirrors quantized_cache_update_arrays for ONE incoming row:
            # the scale only GROWS; existing codes rescale by old/new
            # (exactly 1.0 when unchanged — bit-stable steady state); the
            # row quantizes against the new scale
            amax = _head_amax(xn32)                      # [1, h]
            new_s = jnp.where(valid,
                              jnp.maximum(old_s, amax / _QMAX), old_s)
            factor = jnp.where(
                new_s > 0, old_s / jnp.where(new_s > 0, new_s, 1.0), 1.0)
            fac_hd = seg_dot(factor[:, None, :], expand, exact=True)
            resc = jnp.clip(
                jnp.round(blk_codes.astype(jnp.float32) * fac_hd),
                -_QMAX, _QMAX)
            s_hd = seg_dot(new_s[:, None, :], expand, exact=True)
            safe = jnp.where(s_hd > 0, s_hd, 1.0)
            qrow = jnp.clip(jnp.round(xn32 / safe), -_QMAX, _QMAX)
            codes = jnp.where(off_mask & valid, qrow, resc)  # [1, bs, hd]
            return codes, new_s, s_hd

        old_ks = _sel_row(gks_ref, tkb)
        old_vs = _sel_row(gvs_ref, tkb)
        k_codes, new_ks, ks_hd = _quant_update(kn32, old_ks,
                                               ublk[0])
        v_codes, new_vs, vs_hd = _quant_update(vn32, old_vs,
                                               ublk[1])
        ublk[0] = k_codes.astype(jnp.int8)
        ublk[1] = v_codes.astype(jnp.int8)
        sstage[0] = new_ks
        sstage[1] = new_vs
        kup_f = k_codes * ks_hd          # dequantized local target block
        vup_f = v_codes * vs_hd
    else:
        kup = jnp.where(off_mask & valid,
                        kn_ref[...].astype(ublk.dtype), ublk[0])
        vup = jnp.where(off_mask & valid,
                        vn_ref[...].astype(ublk.dtype), ublk[1])
        ublk[0] = kup
        ublk[1] = vup
        kup_f = kup.astype(jnp.float32)
        vup_f = vup.astype(jnp.float32)

    @pl.when(valid)
    def _writeback():
        wk = pltpu.make_async_copy(ublk.at[0], ko_hbm.at[pl.ds(blk, 1)],
                                   usem.at[0])
        wv = pltpu.make_async_copy(ublk.at[1], vo_hbm.at[pl.ds(blk, 1)],
                                   usem.at[1])
        wk.start()
        wv.start()
        if quant:
            sk = pltpu.make_async_copy(sstage.at[0],
                                       kso_hbm.at[pl.ds(blk, 1)],
                                       usem.at[2])
            sv = pltpu.make_async_copy(sstage.at[1],
                                       vso_hbm.at[pl.ds(blk, 1)],
                                       usem.at[3])
            sk.start()
            sv.start()
            sk.wait()
            sv.wait()
        # writes must complete before the stream below may read the same
        # HBM region (the target block's streamed copy is discarded, but
        # an in-flight overlapping read/write would be undefined)
        wk.wait()
        wv.wait()

    # -- 2. streamed attention over the row's valid blocks ------------------
    qf = q_ref[...].astype(jnp.float32)                  # [1, 1, hd]
    # clamp to >= 1 block: the pre-loop prefetch starts unconditionally
    # and a zero-trip loop would leave its semaphore unbalanced (padding
    # rows read one garbage block; their output is ignored)
    num_kb = jnp.clip((length + bs - 1) // bs, 1, maxb)

    def _copies(slot_i, kb):
        b_kb = jnp.clip(tbl_ref[r, kb], 0, nb - 1)
        return (pltpu.make_async_copy(k_hbm.at[pl.ds(b_kb, 1)],
                                      kbuf.at[slot_i], sem.at[slot_i, 0]),
                pltpu.make_async_copy(v_hbm.at[pl.ds(b_kb, 1)],
                                      vbuf.at[slot_i], sem.at[slot_i, 1]))

    for c_ in _copies(0, 0):
        c_.start()

    def body(kb, carry):
        m, l, acc = carry            # m, l: [1,1,h]; acc: [1,1,hd] fp32
        sl = jax.lax.rem(kb, 2)

        @pl.when(kb + 1 < num_kb)
        def _prefetch():
            for c_ in _copies(1 - sl, kb + 1):
                c_.start()

        kd, vd = _copies(sl, kb)
        kd.wait()
        is_t = valid & (kb == tkb)
        kf = kbuf[sl].astype(jnp.float32)                # [1, bs, hd]
        if quant:
            ksel = jnp.where(is_t, new_ks, _sel_row(gks_ref, kb))
            kf = kf * seg_dot(ksel[:, None, :], expand, exact=True)
        kf = jnp.where(is_t, kup_f, kf)
        s = seg_dot(kf * qf, seg) * scale                # [1, bs, h]
        pos = kb * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs, h), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        vd.wait()
        vf = vbuf[sl].astype(jnp.float32)
        if quant:
            vsel = jnp.where(is_t, new_vs, _sel_row(gvs_ref, kb))
            vf = vf * seg_dot(vsel[:, None, :], expand, exact=True)
        vf = jnp.where(is_t, vup_f, vf)
        pexp = seg_dot(p, expand)                        # [1, bs, hd]
        pv = jnp.sum(pexp * vf, axis=1, keepdims=True)
        acc_new = acc * seg_dot(alpha, expand, exact=True) + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((1, 1, h), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((1, 1, h), jnp.float32)
    acc0 = jnp.zeros((1, 1, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    l_exp = seg_dot(l, expand, exact=True)
    o_ref[...] = (acc / jnp.maximum(l_exp, 1e-30)).astype(o_ref.dtype)


def _ragged_kernel_call(q, k_new, v_new, k_blocks, v_blocks, block_table,
                        pos0, kv_lens, slots, k_scales, v_scales, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, c, h, d = q.shape
    nb, bs = int(k_blocks.shape[0]), int(k_blocks.shape[1])
    hd = h * d
    quant = k_scales is not None
    pool_dt = k_blocks.dtype
    tbl = jnp.asarray(block_table, jnp.int32)
    maxb = int(tbl.shape[1])
    del pos0   # the kernel masks by kv_lens; pos0 == kv_lens - 1 at C=1
    lens_i = jnp.asarray(kv_lens, jnp.int32).reshape(b)
    slots_i = jnp.asarray(slots, jnp.int32).reshape(b)
    anyspace = getattr(pltpu, "HBM", pltpu.ANY)   # 0.4.x: ANY (HBM is the
    #                                               newer-jax name)
    in_specs = [
        pl.BlockSpec((1, 1, hd), lambda r, *pre: (r, 0, 0)),     # q
        pl.BlockSpec((1, 1, hd), lambda r, *pre: (r, 0, 0)),     # k_new
        pl.BlockSpec((1, 1, hd), lambda r, *pre: (r, 0, 0)),     # v_new
        pl.BlockSpec(memory_space=anyspace),                     # k pool
        pl.BlockSpec(memory_space=anyspace),                     # v pool
    ]
    args = [q.reshape(b, c, hd), k_new.reshape(b, c, hd),
            v_new.reshape(b, c, hd), k_blocks.reshape(nb, bs, hd),
            v_blocks.reshape(nb, bs, hd)]
    out_shape = [jax.ShapeDtypeStruct((b, 1, hd), q.dtype),
                 jax.ShapeDtypeStruct((nb, bs, hd), pool_dt),
                 jax.ShapeDtypeStruct((nb, bs, hd), pool_dt)]
    out_specs = [pl.BlockSpec((1, 1, hd), lambda r, *pre: (r, 0, 0)),
                 pl.BlockSpec(memory_space=anyspace),
                 pl.BlockSpec(memory_space=anyspace)]
    scratch = [
        pltpu.VMEM((2, 1, bs, hd), pool_dt),      # k stream double-buffer
        pltpu.VMEM((2, 1, bs, hd), pool_dt),      # v stream double-buffer
        pltpu.SemaphoreType.DMA((2, 2)),
        pltpu.VMEM((2, 1, bs, hd), pool_dt),      # target block k/v
        pltpu.SemaphoreType.DMA((4,)),
    ]
    # aliasing indices INCLUDE the scalar-prefetch args (lens=0, slots=1,
    # tables=2, q=3, k_new=4, v_new=5, pools=6/7; int8 adds gathered
    # scale views 8/9 and the scale tables 10/11)
    aliases = {6: 1, 7: 2}
    if quant:
        safe_tbl = jnp.clip(tbl, 0, nb - 1)
        in_specs += [
            pl.BlockSpec((1, maxb, h), lambda r, *pre: (r, 0, 0)),
            pl.BlockSpec((1, maxb, h), lambda r, *pre: (r, 0, 0)),
            pl.BlockSpec(memory_space=anyspace),
            pl.BlockSpec(memory_space=anyspace),
        ]
        args += [jnp.take(k_scales, safe_tbl, axis=0),
                 jnp.take(v_scales, safe_tbl, axis=0),
                 k_scales, v_scales]
        out_shape += [jax.ShapeDtypeStruct((nb, h), jnp.float32),
                      jax.ShapeDtypeStruct((nb, h), jnp.float32)]
        out_specs += [pl.BlockSpec(memory_space=anyspace),
                      pl.BlockSpec(memory_space=anyspace)]
        aliases.update({10: 3, 11: 4})
        scratch.append(pltpu.VMEM((2, 1, h), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kernel = functools.partial(_ragged_fused_kernel, bs=bs, h=h, d=d,
                               nb=nb, maxb=maxb, scale=scale, quant=quant)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(lens_i, slots_i, tbl, *args)
    o = outs[0].reshape(b, c, h, d)
    k2 = outs[1].reshape(k_blocks.shape)
    v2 = outs[2].reshape(v_blocks.shape)
    if quant:
        return o, k2, v2, outs[3], outs[4]
    return o, k2, v2


# ---------------------------------------------------------------------------
# XLA array-level fallback pieces
# ---------------------------------------------------------------------------

def _folded_quant_attention(q, k_blocks, v_blocks, k_scales, v_scales,
                            block_table, pos0, scale):
    """int8 paged attention WITHOUT the dequantizing gather: int8 CODES
    are gathered (¼ of the fp32 dequant materialization the bucketed
    path's `quantized_gather_kv_arrays` pays) and the per-block-per-head
    scales fold into the logits (K side) and probabilities (V side) —
    exact in real arithmetic because the scale is constant along the
    contracted head_dim axis."""
    b, s, h, d = q.shape
    nb, bs = k_blocks.shape[0], k_blocks.shape[1]
    tbl = jnp.clip(jnp.asarray(block_table, jnp.int32), 0, nb - 1)
    maxb = tbl.shape[1]
    s_pad = maxb * bs
    kg = jnp.take(k_blocks, tbl, axis=0).reshape(b, s_pad, h, d)
    vg = jnp.take(v_blocks, tbl, axis=0).reshape(b, s_pad, h, d)
    # per-position scales: [B, maxb, H] broadcast over the block rows —
    # [B, S_pad, H] fp32, a D-th of the dequantized-KV footprint
    ksg = jnp.broadcast_to(
        jnp.take(k_scales, tbl, axis=0)[:, :, None, :],
        (b, maxb, bs, h)).reshape(b, s_pad, h)
    vsg = jnp.broadcast_to(
        jnp.take(v_scales, tbl, axis=0)[:, :, None, :],
        (b, maxb, bs, h)).reshape(b, s_pad, h)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kg.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    logits = logits * jnp.transpose(ksg, (0, 2, 1))[:, :, None, :]
    q_pos = jnp.asarray(pos0, jnp.int32)[:, None] + jnp.arange(
        s, dtype=jnp.int32)[None, :]
    k_pos = jnp.arange(s_pad, dtype=jnp.int32)
    causal = k_pos[None, None, :] <= q_pos[:, :, None]
    logits = jnp.where(causal[:, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    pw = probs * jnp.transpose(vsg, (0, 2, 1))[:, :, None, :]
    out = jnp.einsum("bhqk,bkhd->bqhd", pw, vg.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def ragged_paged_attention_arrays(q, k_new, v_new, k_blocks, v_blocks,
                                  block_table, pos0, kv_lens, slots,
                                  k_scales=None, v_scales=None, scale=None):
    """Fused cache-update + causal paged attention for a ragged batch in
    ONE fixed-shape program.

    q, k_new, v_new: [B, C, H, D] — the current tokens (C = 1 at decode;
                     C > 1 for a prefill-continuation chunk, or a
                     speculative-decode VERIFY batch: position 0 is the
                     row's last real token and positions 1..k its draft
                     tokens).  Rows may sit at DIFFERENT absolute
                     positions (mixed prefill/decode batches) and
                     padding rides along at BOTH granularities: whole
                     padding rows AND, in a verify batch, a row's unused
                     trailing draft positions — either way a dropped
                     slot suppresses the write and the caller ignores
                     the output.  Write-then-attend makes in-chunk
                     causality the pool's own: draft j's query sees
                     draft j-1's K/V because the update lands before the
                     attention reads, under the same per-position causal
                     mask as sequential decode — which is what lets the
                     engine score all k+1 positions in ONE launch and
                     stay token-identical to step-by-step greedy.
    k_blocks/v_blocks: [num_blocks, block_size, H, D] physical pools
                     (fp, or int8 codes with `k_scales`/`v_scales`
                     [num_blocks, H] per-block-per-head scale pools).
    block_table:     [B, max_blocks] int32 per-row logical→physical map.
    pos0:            [B] int32 absolute position of each row's first
                     query (== context length before this chunk).
    kv_lens:         [B] int32 valid KEY count per row AFTER the write
                     (pos0 + valid queries) — the kernel's block-loop
                     bound; ignored by the masked fallback.
    slots:           [B, C] int32 physical write slots; out-of-range
                     entries (padding / evicted rows) are dropped.

    Returns ``(out, k_blocks', v_blocks')`` — plus ``(k_scales',
    v_scales')`` in quantized mode.  The new tokens' K/V are written to
    their slots INSIDE the program (write-then-attend, the dense cache
    ordering), so callers never run a separate cache-update pass.
    """
    b, c, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    quant = k_scales is not None
    if quant != (v_scales is not None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    if _ragged_kernel_ok(q, k_blocks, c, quant):
        return _ragged_kernel_call(q, k_new, v_new, k_blocks, v_blocks,
                                   block_table, pos0, kv_lens, slots,
                                   k_scales, v_scales, scale)
    if not quant:
        # bitwise the reference composition — the fp parity contract
        k2 = paged_cache_update_arrays(k_blocks, k_new, slots)
        v2 = paged_cache_update_arrays(v_blocks, v_new, slots)
        out = paged_attention_arrays(q, k2, v2, block_table, pos0,
                                     scale=scale)
        return out, k2, v2
    k2, ks2 = quantized_cache_update_arrays(k_blocks, k_scales, k_new,
                                            slots)
    v2, vs2 = quantized_cache_update_arrays(v_blocks, v_scales, v_new,
                                            slots)
    out = _folded_quant_attention(q, k2, v2, ks2, vs2, block_table, pos0,
                                  scale)
    return out, k2, v2, ks2, vs2
