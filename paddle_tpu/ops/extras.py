"""Long-tail tensor-op surface (reference: python/paddle/tensor/math.py /
stat.py / search.py / manipulation.py entries not covered by the core op
modules — each a pure jnp formulation XLA fuses; no phi kernel registry
needed).

Includes the reference's inplace-variant methods (reshape_/squeeze_/...),
which on immutable XLA arrays are "replace my _data and bump the inplace
version" (the tape's version counter then guards stale-backward use, same
contract as the reference's inplace version check).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply

__all__ = [
    "add_n", "broadcast_shape", "broadcast_tensors", "bucketize", "complex",
    "count_nonzero", "crop", "diagflat", "diff", "dist", "floor_mod",
    "frexp", "heaviside", "histogram", "index_add", "kthvalue", "logit",
    "logspace", "median", "mode", "multiplex", "mv", "nanmean", "nanmedian",
    "nanquantile", "nansum", "poisson", "quantile", "randint_like", "rank",
    "renorm", "reverse", "scatter_nd", "sgn", "shape", "standard_normal",
    "std", "t", "take", "tril_indices", "triu_indices", "unique_consecutive",
    "unstack", "var", "vsplit", "is_tensor", "is_complex",
    "is_floating_point", "is_integer", "tolist",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# -- type predicates (reference: tensor/attribute.py) -----------------------

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(_arr(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_arr(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_arr(x).dtype, jnp.integer)


def rank(x):
    return Tensor(jnp.asarray(_arr(x).ndim, jnp.int32))


def shape(x):
    """paddle.shape: runtime shape as an int32 tensor (static under XLA)."""
    return Tensor(jnp.asarray(_arr(x).shape, jnp.int32))


def tolist(x):
    return np.asarray(_arr(x)).tolist()


# -- elementwise / math -----------------------------------------------------

def add_n(inputs, name=None):
    ts = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    return apply(lambda *a: sum(a[1:], a[0]), *ts, name="add_n")


def floor_mod(x, y, name=None):
    return apply(lambda a, b: jnp.mod(a, b), _t(x), _t(y), name="floor_mod")


def heaviside(x, y, name=None):
    return apply(lambda a, b: jnp.heaviside(a, b).astype(a.dtype),
                 _t(x), _t(y), name="heaviside")


def logit(x, eps=None, name=None):
    def fn(a):
        a32 = a.astype(jnp.float32)
        if eps is not None:
            a32 = jnp.clip(a32, eps, 1.0 - eps)
        out = jnp.log(a32 / (1.0 - a32))
        if eps is None:
            out = jnp.where((a32 < 0) | (a32 > 1), jnp.nan, out)
        return out.astype(a.dtype)

    return apply(fn, _t(x), name="logit")


def sgn(x, name=None):
    """sign for real dtypes; unit-modulus complex for complex dtypes."""

    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-38))
        return jnp.sign(a)

    return apply(fn, _t(x), name="sgn")


def frexp(x, name=None):
    return apply(lambda a: tuple(jnp.frexp(a)), _t(x), name="frexp")


def complex(real, imag, name=None):
    return apply(lambda r, i: jax.lax.complex(r, i), _t(real), _t(imag),
                 name="complex")


def mv(x, vec, name=None):
    return apply(lambda a, b: a @ b, _t(x), _t(vec), name="mv")


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1).astype(jnp.float32)
        if p == float("inf"):
            out = jnp.max(jnp.abs(d))
        elif p == 0:
            out = jnp.sum(d != 0).astype(jnp.float32)
        else:
            out = jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
        return out.astype(a.dtype)

    return apply(fn, _t(x), _t(y), name="dist")


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along `axis` (reference renorm_op)."""

    def fn(a):
        red = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a.astype(jnp.float32)) ** p,
                        axis=red, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return (a * factor).astype(a.dtype)

    return apply(fn, _t(x), name="renorm")


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference multiplex_op):
    out[i] = inputs[index[i]][i]."""
    ts = list(inputs)

    def fn(idx, *cands):
        stacked = jnp.stack(cands, axis=0)            # [C, B, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1).astype(jnp.int32), rows]

    return apply(fn, _t(index), *[_t(c) for c in ts], name="multiplex")


# -- reductions / statistics ------------------------------------------------

def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.count_nonzero(a, axis=axis, keepdims=keepdim
                                             ).astype(jnp.int64),
                 _t(x), name="count_nonzero")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype) if dtype is not None else None
    return apply(lambda a: jnp.nansum(a if d is None else a.astype(d),
                                      axis=axis, keepdims=keepdim),
                 _t(x), name="nansum")


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmean(a, axis=axis, keepdims=keepdim),
                 _t(x), name="nanmean")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.std(a, axis=axis, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x), name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.var(a, axis=axis, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x), name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(a):
        if mode == "avg":
            return jnp.median(a, axis=axis, keepdims=keepdim)
        # mode="min": lower of the two middle values (reference contract)
        n = a.shape[axis] if axis is not None else a.size
        k = (n - 1) // 2
        srt = jnp.sort(a.reshape(-1) if axis is None else a, axis=-1 if axis is None else axis)
        out = jnp.take(srt, k, axis=-1 if axis is None else axis)
        return jnp.expand_dims(out, axis) if (keepdim and axis is not None) else out

    return apply(fn, _t(x), name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                 _t(x), name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda a: jnp.quantile(a.astype(jnp.float32), jnp.asarray(q),
                                        axis=axis, keepdims=keepdim,
                                        method=interpolation),
                 _t(x), name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanquantile(a.astype(jnp.float32),
                                           jnp.asarray(q), axis=axis,
                                           keepdims=keepdim),
                 _t(x), name="nanquantile")


def histogram(input, bins=100, min=0, max=0, name=None):
    def fn(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (None, None)
        if lo is None:
            lo = jnp.min(a).astype(jnp.float32)
            hi = jnp.max(a).astype(jnp.float32)
        counts, _ = jnp.histogram(a.astype(jnp.float32).reshape(-1),
                                  bins=bins, range=(lo, hi))
        return counts.astype(jnp.int64)

    return apply(fn, _t(input), name="histogram")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        srt = jnp.sort(a, axis=axis)
        idx = jnp.argsort(a, axis=axis)
        val = jnp.take(srt, k - 1, axis=axis)
        ind = jnp.take(idx, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            val = jnp.expand_dims(val, axis)
            ind = jnp.expand_dims(ind, axis)
        return val, ind

    return apply(fn, _t(x), name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (ties: the largest, matching the
    reference's last-in-sorted-order pick)."""

    def fn(a):
        srt = jnp.sort(a, axis=axis)

        def most_freq(row):
            # counts via comparing each element against the sorted row
            eq = row[:, None] == row[None, :]
            counts = eq.sum(-1)
            best = jnp.argmax(counts + jnp.arange(row.shape[0]) * 1e-9)
            return row[best]

        moved = jnp.moveaxis(srt, axis, -1)
        lead = moved.shape[:-1]
        flat = moved.reshape(-1, moved.shape[-1])
        vals_flat = jax.vmap(most_freq)(flat)               # [rows]
        orig = jnp.moveaxis(a, axis, -1)
        flat_orig = orig.reshape(-1, orig.shape[-1])
        idx_flat = jax.vmap(lambda r, v: jnp.argmax(r == v))(flat_orig,
                                                             vals_flat)
        vals_f = vals_flat.reshape(lead)
        idx = idx_flat.reshape(lead).astype(jnp.int64)
        if keepdim:
            vals_f = jnp.expand_dims(vals_f, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals_f, idx

    return apply(fn, _t(x), name="mode")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [_t(x)]
    has_pre = prepend is not None
    has_app = append is not None
    if has_pre:
        args.append(_t(prepend))
    if has_app:
        args.append(_t(append))

    def fn(a, *rest):
        pre = rest[0] if has_pre else None
        app = rest[1 if has_pre else 0] if has_app else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)

    return apply(fn, *args, name="diff")


# -- shape / indexing -------------------------------------------------------

def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs, name=None):
    ts = list(inputs)

    def fn(*arrs):
        shape = np.broadcast_shapes(*[a.shape for a in arrs])
        return tuple(jnp.broadcast_to(a, shape) for a in arrs)

    return apply(fn, *[_t(c) for c in ts], name="broadcast_tensors")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def fn(a, seq):
        side = "right" if right else "left"
        out = jnp.searchsorted(seq, a, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply(fn, _t(x), _t(sorted_sequence), name="bucketize")


def crop(x, shape=None, offsets=None, name=None):
    def fn(a):
        shp = [a.shape[i] if (shape is None or shape[i] == -1) else shape[i]
               for i in range(a.ndim)]
        off = [0] * a.ndim if offsets is None else list(offsets)
        return jax.lax.dynamic_slice(a, off, shp)

    return apply(fn, _t(x), name="crop")


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=offset), _t(x), name="diagflat")


def index_add(x, index, axis, value, name=None):
    def fn(a, idx, val):
        return a.at[(slice(None),) * (axis % a.ndim)
                    + (idx.astype(jnp.int32),)].add(val)

    return apply(fn, _t(x), _t(index), _t(value), name="index_add")


def scatter_nd(index, updates, shape, name=None):
    """out[index[i]] += updates[i] over an all-zeros tensor of `shape`
    (reference scatter_nd_op: additive for duplicate indices)."""

    def fn(idx, upd):
        out = jnp.zeros(tuple(shape), upd.dtype)
        k = idx.shape[-1]
        flat_idx = idx.reshape(-1, k).astype(jnp.int32)
        upd_flat = upd.reshape((flat_idx.shape[0],) + tuple(shape[k:]))
        return out.at[tuple(flat_idx[:, i] for i in range(k))].add(upd_flat)

    return apply(fn, _t(index), _t(updates), name="scatter_nd")


def reverse(x, axis, name=None):
    ax = [axis] if isinstance(axis, int) else list(axis)
    return apply(lambda a: jnp.flip(a, axis=ax), _t(x), name="reverse")


def take(x, index, mode="raise", name=None):
    xt, it = _t(x), _t(index)
    n_total = int(np.prod(xt.shape)) if xt.ndim else 1
    if mode == "raise" and not isinstance(it._data, jax.core.Tracer):
        # reference CPU contract: out-of-range raises. Under a jit trace
        # values are unknown; indices clamp (XLA gather semantics), same
        # as the reference GPU kernel which cannot raise either.
        inp = np.asarray(it._data)
        if inp.size and (int(inp.min()) < -n_total or
                         int(inp.max()) >= n_total):
            raise ValueError(
                f"take index out of range for tensor of {n_total} elements "
                f"(got [{int(inp.min())}, {int(inp.max())}])")

    def fn(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int32)
        if mode == "wrap":
            ii = jnp.mod(ii, n)
        else:
            ii = jnp.clip(ii, -n, n - 1)
        ii = jnp.where(ii < 0, ii + n, ii)
        return flat[ii]

    return apply(fn, xt, it, name="take")


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), jnp.int64))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), jnp.int64))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """Deduplicate consecutive runs (host-side: dynamic output shape, same
    as the reference CPU kernel's contract)."""
    a = np.asarray(_arr(x))
    if axis is None:
        a = a.reshape(-1)
        change = np.ones(len(a), bool)
        change[1:] = a[1:] != a[:-1]
        out = a[change]
        inv = np.cumsum(change) - 1
        counts = np.diff(np.append(np.nonzero(change)[0], len(a)))
    else:
        moved = np.moveaxis(a, axis, 0)
        change = np.ones(moved.shape[0], bool)
        change[1:] = (moved[1:] != moved[:-1]).reshape(moved.shape[0] - 1, -1).any(1)
        out = np.moveaxis(moved[change], 0, axis)
        inv = np.cumsum(change) - 1
        counts = np.diff(np.append(np.nonzero(change)[0], moved.shape[0]))
    res = [Tensor(jnp.asarray(out))]
    if return_inverse:
        res.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        res.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return tuple(res) if len(res) > 1 else res[0]


def unstack(x, axis=0, num=None, name=None):
    n = _arr(x).shape[axis] if num is None else num
    out = apply(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)),
                _t(x), name="unstack")
    return list(out) if isinstance(out, tuple) else [out]


def vsplit(x, num_or_indices, name=None):
    def fn(a):
        return tuple(jnp.split(a, num_or_indices, axis=0))

    out = apply(fn, _t(x), name="vsplit")
    return list(out) if isinstance(out, tuple) else [out]


def t(x, name=None):
    def fn(a):
        assert a.ndim <= 2, "paddle.t expects a 0/1/2-D tensor"
        return a.T

    return apply(fn, _t(x), name="t")


# -- creation / random ------------------------------------------------------

def logspace(start, stop, num, base=10.0, dtype="float32", name=None):
    from ..core.dtype import convert_dtype

    return Tensor(jnp.logspace(float(start), float(stop), int(num),
                               base=float(base),
                               dtype=convert_dtype(dtype)))


def standard_normal(shape, dtype="float32", name=None):
    from ..core import random as _rng
    from ..core.dtype import convert_dtype

    key = _rng.next_key()
    return Tensor(jax.random.normal(key, tuple(shape), convert_dtype(dtype)))


def poisson(x, name=None):
    from ..core import random as _rng

    key = _rng.next_key()
    return apply(lambda a: jax.random.poisson(key, a.astype(jnp.float32)
                                              ).astype(a.dtype),
                 _t(x), name="poisson")


def randint_like(x, low=0, high=None, dtype=None, name=None):
    from ..core import random as _rng

    a = _arr(x)
    lo, hi = (0, low) if high is None else (low, high)
    key = _rng.next_key()
    out_dtype = a.dtype if dtype is None else dtype
    from ..core.dtype import convert_dtype

    return Tensor(jax.random.randint(key, a.shape, int(lo), int(hi)
                                     ).astype(convert_dtype(out_dtype)))


# -- inplace free functions + shape check -----------------------------------

def _inplace_variant(meth_name):
    """Inplace rebind, same contract as __setitem__ (ops/__init__._setitem):
    besides swapping _data (which bumps the inplace version for the tape
    guard), the tensor must adopt the producing op's grad node — otherwise
    the op silently drops out of the autograd graph and backward uses the
    OLD producer's pullback (wrong gradients, no error)."""

    from ._inplace import make_inplace

    return make_inplace(lambda snap, *a, **k: getattr(snap, meth_name)(*a, **k),
                        name=meth_name + "_")


reshape_ = _inplace_variant("reshape")
squeeze_ = _inplace_variant("squeeze")
unsqueeze_ = _inplace_variant("unsqueeze")
tanh_ = _inplace_variant("tanh")
scatter_ = _inplace_variant("scatter")


# -- diagonal fills (reference tensor/manipulation.py:913 fill_diagonal_,
#    :975 fill_diagonal_tensor_ — phi kernels fill_diagonal /
#    fill_diagonal_tensor) -------------------------------------------------

def _diag_mask_2d(n, m, offset, wrap):
    """Boolean [n, m] mask of the filled diagonal. Flat-stride formulation
    (the reference kernel iterates flat indices with stride m+1; numpy
    fill_diagonal(wrap=True) semantics for tall matrices)."""
    flat = np.zeros(n * m, bool)
    start = offset if offset >= 0 else -offset * m
    if wrap:
        idx = np.arange(start, n * m, m + 1)
    else:
        cnt = min(n - max(-offset, 0), m - max(offset, 0))
        idx = start + np.arange(max(cnt, 0)) * (m + 1)
    flat[idx[idx < n * m]] = True
    return jnp.asarray(flat.reshape(n, m))


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Out-of-place fill_diagonal (the inplace method wraps this). 2-D:
    offset/wrap supported; N-D: all dims equal, main diagonal only
    (reference FillDiagonalKernel contract)."""
    x = _t(x)
    shp = x.shape
    if len(shp) < 2:
        raise ValueError("fill_diagonal needs at least a 2-D tensor")
    if len(shp) == 2:
        mask = _diag_mask_2d(shp[0], shp[1], int(offset), bool(wrap))
    else:
        if len(set(shp)) != 1:
            raise ValueError(
                "fill_diagonal on >2-D tensors requires equal dims")
        if offset:
            raise ValueError("offset must be 0 for >2-D fill_diagonal")
        n, nd = shp[0], len(shp)
        mask = jnp.zeros(shp, bool).at[(jnp.arange(n),) * nd].set(True)
    return apply(lambda a: jnp.where(mask, jnp.asarray(value, a.dtype), a),
                 x, name="fill_diagonal")


fill_diagonal_ = _inplace_variant("fill_diagonal")


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Fill the (dim1, dim2)-plane diagonals of x with tensor y
    (reference tensor/manipulation.py:1009; y's trailing dim is the
    diagonal length, leading dims are x's remaining dims)."""
    x, y = _t(x), _t(y)
    nd = x.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if d1 == d2:
        raise ValueError("dim1 and dim2 must differ")
    perm = [i for i in range(nd) if i not in (d1, d2)] + [d1, d2]
    inv = np.argsort(perm)
    n, m = x.shape[d1], x.shape[d2]
    dlen = min(n - max(-offset, 0), m - max(offset, 0))
    if dlen <= 0:
        raise ValueError("offset leaves an empty diagonal")
    rows = jnp.arange(dlen) + max(-offset, 0)
    cols = jnp.arange(dlen) + max(offset, 0)

    def fill(a, yv):
        moved = jnp.transpose(a, perm)
        filled = moved.at[..., rows, cols].set(yv.astype(a.dtype))
        return jnp.transpose(filled, inv)

    return apply(fill, x, y, name="fill_diagonal_tensor")


fill_diagonal_tensor_ = _inplace_variant("fill_diagonal_tensor")


def check_shape(shape):
    """Validate a shape argument (reference fluid/layers/utils.py
    check_shape: ints or a 1-D int tensor; -1 allowed once)."""
    if isinstance(shape, Tensor):
        shape = tolist(shape)
    shape = list(shape)
    for s in shape:
        if not isinstance(s, (int, np.integer)):
            raise TypeError(f"shape entries must be int, got {type(s)}")
    if sum(1 for s in shape if s == -1) > 1:
        raise ValueError("only one dimension may be -1 in a shape")
    return shape


__all__ += ["reshape_", "squeeze_", "unsqueeze_", "tanh_", "scatter_",
            "check_shape", "fill_diagonal", "fill_diagonal_",
            "fill_diagonal_tensor", "fill_diagonal_tensor_"]


# -- Tensor-method surface completion (reference tensor/__init__.py method
# registration: linalg methods, inplace arithmetic variants, random fills) --

def _attach_tensor_methods():
    from .. import linalg as _la
    from ._inplace import make_inplace
    from ..core import random as _rng

    # linalg functions as methods (reference: Tensor.cholesky etc.)
    for _n in ("cholesky", "cholesky_solve", "cond", "corrcoef", "cov",
               "eig", "eigvals", "eigvalsh", "inverse", "lstsq", "lu",
               "lu_unpack", "matrix_power", "multi_dot", "norm", "qr",
               "solve", "triangular_solve"):
        if not hasattr(Tensor, _n) and hasattr(_la, _n):
            setattr(Tensor, _n, getattr(_la, _n))

    # inplace arithmetic/rounding variants over existing methods
    for _n in ("add", "subtract", "remainder", "clip", "ceil", "floor",
               "round", "exp", "sqrt", "rsqrt", "reciprocal", "erfinv",
               "lerp", "scale", "flatten", "put_along_axis"):
        meth = getattr(Tensor, _n, None)
        if meth is not None and not hasattr(Tensor, _n + "_"):
            setattr(Tensor, _n + "_",
                    make_inplace(meth, name=_n + "_"))

    def uniform_(self, min=-1.0, max=1.0, seed=0, name=None):
        """In-place uniform refill (reference Tensor.uniform_): a data
        swap, not a taped op (matches the reference's non-differentiable
        random fill)."""
        key = _rng.next_key()
        self._data = jax.random.uniform(
            key, self.shape, self._data.dtype, minval=min, maxval=max)
        return self

    def exponential_(self, lam=1.0, name=None):
        key = _rng.next_key()
        self._data = (jax.random.exponential(key, self.shape)
                      / lam).astype(self._data.dtype)
        return self

    Tensor.uniform_ = uniform_
    Tensor.exponential_ = exponential_

    def create_tensor(self, dtype=None, name=None):
        return Tensor(jnp.zeros((), dtype or self._data.dtype))

    def create_parameter(self, shape, dtype=None, **kw):
        import paddle_tpu as _p

        return _p.create_parameter(shape, dtype or str(self._data.dtype),
                                   **kw)

    def increment(self, value=1.0):
        from . import increment as _inc

        return _inc(self, value)

    Tensor.create_tensor = create_tensor
    Tensor.create_parameter = create_parameter
    Tensor.increment = increment

