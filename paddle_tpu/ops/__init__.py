"""Framework op namespace + Tensor method attachment.

Mirrors the reference's `python/paddle/tensor/__init__.py` pattern: ops are
plain functions; a registration step attaches them as Tensor methods and
installs the arithmetic/indexing dunder operators.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ._inplace import _autograd_snapshot, _inplace_rebind, make_inplace

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .array import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403

from . import array, creation, math, manipulation, logic, extras
# serving-side paged-KV attention: importable as ops.paged_attention —
# array-level only, deliberately NOT star-exported into the top-level
# paddle namespace (it is an engine primitive, not a user tensor op)
from . import paged_attention  # noqa: F401
# low-bit quantized storage/compute primitives (paddle_tpu.lowbit's op
# layer) — array-level only, same non-export rationale as paged_attention
from . import lowbit  # noqa: F401
# fused ragged paged attention (the serving decode workhorse: one
# fixed-shape program with in-program cache update + int8 dequant) —
# array-level only, same non-export rationale as paged_attention
from . import ragged_paged_attention  # noqa: F401

__all__ = (
    list(creation.__all__)
    + list(math.__all__)
    + list(manipulation.__all__)
    + list(logic.__all__)
    + list(array.__all__)
    + list(extras.__all__)
)


# -- dunder operators -------------------------------------------------------

def _coerce(other):
    if isinstance(other, Tensor):
        return other
    return Tensor(jnp.asarray(other))


def _install_operators():
    from . import math as m, logic as lg

    def binop(fn):
        def op(self, other):
            return fn(self, _coerce(other))

        return op

    def rbinop(fn):
        def op(self, other):
            return fn(_coerce(other), self)

        return op

    Tensor.__add__ = binop(m.add)
    Tensor.__radd__ = rbinop(m.add)
    Tensor.__sub__ = binop(m.subtract)
    Tensor.__rsub__ = rbinop(m.subtract)
    Tensor.__mul__ = binop(m.multiply)
    Tensor.__rmul__ = rbinop(m.multiply)
    Tensor.__truediv__ = binop(m.divide)
    Tensor.__rtruediv__ = rbinop(m.divide)
    Tensor.__floordiv__ = binop(m.floor_divide)
    Tensor.__mod__ = binop(m.remainder)
    Tensor.__pow__ = binop(m.pow)
    Tensor.__rpow__ = rbinop(m.pow)
    Tensor.__matmul__ = binop(m.matmul)
    Tensor.__neg__ = lambda self: m.neg(self)
    Tensor.__abs__ = lambda self: m.abs(self)
    Tensor.__eq__ = lambda self, o: lg.equal(self, o)
    Tensor.__ne__ = lambda self, o: lg.not_equal(self, o)
    Tensor.__lt__ = lambda self, o: lg.less_than(self, o)
    Tensor.__le__ = lambda self, o: lg.less_equal(self, o)
    Tensor.__gt__ = lambda self, o: lg.greater_than(self, o)
    Tensor.__ge__ = lambda self, o: lg.greater_equal(self, o)
    Tensor.__invert__ = lambda self: lg.logical_not(self)


def _prep_index(item):
    """Normalize an indexing expression; Tensor indices become jax arrays."""
    if not isinstance(item, tuple):
        item = (item,)
    out = []
    for it in item:
        if isinstance(it, Tensor):
            arr = it._data
            if arr.dtype == jnp.bool_:
                # boolean mask → host advanced indexing (dynamic shape)
                out.append(jax.device_get(arr))
            else:
                out.append(arr)
        else:
            out.append(it)
    return tuple(out)


def _getitem(self, item):
    import builtins

    idx = _prep_index(item)
    import numpy as np

    if builtins.any(isinstance(i, np.ndarray) and i.dtype == bool for i in idx):
        # dynamic-shape path, non-jittable (same as reference masked_select)
        return Tensor(jnp.asarray(np.asarray(self._data)[
            tuple(np.asarray(i) if hasattr(i, "shape") else i for i in idx)
        ]))
    return apply(lambda a: a[idx], self, name="getitem")


def _setitem(self, item, value):
    idx = _prep_index(item)
    src = _autograd_snapshot(self)
    if isinstance(value, Tensor):
        out = apply(
            lambda a, v: a.at[idx].set(v.astype(a.dtype)), src, value, name="setitem"
        )
    else:
        out = apply(lambda a: a.at[idx].set(value), src, name="setitem")
    # In-place rebind (reference: __setitem__ is an inplace op on the eager
    # tensor; autograd-wise the tensor now points at the new producing node,
    # whose recorded input is the frozen snapshot).
    _inplace_rebind(self, out)


_METHODS = {}


def _install_methods():
    import types

    namespaces = [creation, math, manipulation, logic, extras]
    skip = {"zeros", "ones", "full", "empty", "arange", "linspace", "eye",
            "rand", "randn", "randint", "uniform", "normal", "randperm",
            "meshgrid", "assign"}
    for ns in namespaces:
        for name in ns.__all__:
            fn = getattr(ns, name)
            if name in skip or not callable(fn):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
            _METHODS[name] = fn
    # aliases matching paddle.Tensor surface
    Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
    Tensor.cast = lambda self, dtype: manipulation.cast(self, dtype)
    # reshape_/squeeze_/unsqueeze_/tanh_/scatter_ methods come from
    # ops.extras via the namespace loop above (single source of truth,
    # with full autograd rebinding — see extras._inplace_variant)
    Tensor.t = lambda self: manipulation.transpose(self, list(range(self.ndim))[::-1])
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.scale = lambda self, scale=1.0, bias=0.0, bias_after_scale=True: (
        apply(lambda a: a * scale + bias, self, name="scale")
        if bias_after_scale
        else apply(lambda a: (a + bias) * scale, self, name="scale")
    )
    Tensor.mean_ = Tensor.mean


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = apply(
        (lambda a: a * scale + bias) if bias_after_scale else (lambda a: (a + bias) * scale),
        x,
        name="scale",
    )
    if act == "relu":
        out = apply(lambda a: jnp.maximum(a, 0), out, name="relu")
    return out


def increment(x, value=1.0):
    out = apply(lambda a: a + value, x, name="increment")
    x._data = out._data
    return x


_install_operators()
_install_methods()
# linalg/inplace/random Tensor methods build ON the methods installed above
extras._attach_tensor_methods()

__all__ += ["scale", "increment"]
