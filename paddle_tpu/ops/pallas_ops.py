"""Pallas TPU kernels for the hot paths.

TPU-native replacement for the reference's hand-fused CUDA ops
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_multi_transformer_op.cu — which are full-sequence, non-flash;
SURVEY §5.7): here attention is blockwise/flash-style, O(seq) memory,
written for the MXU (block sizes multiples of 128 lanes) with an XLA
fallback used off-TPU and for odd shapes.

Layout convention: [batch, seq, num_heads, head_dim] (the reference's
fused-attention layout).
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..core import random as _rng

__all__ = [
    "flash_attention", "flash_attention_arrays", "mha_reference",
    "cached_attention_arrays", "attention_path_counts",
    "reset_attention_path_counts",
]

_NEG_INF = -1e30

# ---------------------------------------------------------------------------
# Path-taken debug counters (VERDICT r2 weak #6/#7): the kernel gates fall
# back silently by design; under PTPU_ATTN_DEBUG=1 every gate decision is
# counted so perf cliffs (serving shapes dropping to the O(S^2) path) are
# observable. Counting happens at TRACE time — each compiled program counts
# once per distinct shape, which is exactly the signal wanted.
# ---------------------------------------------------------------------------

import collections as _collections
import os as _os

_PATH_COUNTS: "_collections.Counter[str]" = _collections.Counter()


def _count_path(name):
    if _os.environ.get("PTPU_ATTN_DEBUG") == "1":
        _PATH_COUNTS[name] += 1


def attention_path_counts():
    """{path_name: times_traced} — populated under PTPU_ATTN_DEBUG=1."""
    return dict(_PATH_COUNTS)


def reset_attention_path_counts():
    _PATH_COUNTS.clear()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference (XLA) attention — also the source of the backward pass
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, mask=None, is_causal=False, scale=None,
                  kv_lens=None, segment_ids=None):
    """q,k,v: [B,S,H,D] → [B,S,H,D]. Computed in fp32 accumulation.
    kv_lens: optional [B] int32 valid key lengths (right-padded batch).
    segment_ids: optional [B, S] int32 packed-sequence ids (self-attention
    only): position pairs attend iff their ids match."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        logits = jnp.where(causal, logits, _NEG_INF)
    if kv_lens is not None:
        k_pos = jnp.arange(k.shape[1], dtype=jnp.int32)
        valid = k_pos[None, None, None, :] < jnp.asarray(
            kv_lens, jnp.int32)[:, None, None, None]
        logits = jnp.where(valid, logits, _NEG_INF)
    if segment_ids is not None:
        ids = jnp.asarray(segment_ids, jnp.int32)
        same = ids[:, None, :, None] == ids[:, None, None, :]   # [B,1,Sq,Sk]
        logits = jnp.where(same, logits, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, _NEG_INF)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


# ---------------------------------------------------------------------------
# Pallas flash forward
# ---------------------------------------------------------------------------

def _dot_f32(a, b, transpose_b=False):
    """Matmul keeping operand dtype with fp32 accumulation. bf16 operands
    ride the MXU's fast path (fp32 operands would run ~8x slower on v5e);
    fp32 operands pin HIGHEST precision so the correctness dtype doesn't
    silently truncate to bf16 inside the kernel."""
    dims = (((1,), (1 if transpose_b else 0,)), ((), ()))
    prec = (jax.lax.Precision.HIGHEST
            if a.dtype == jnp.float32 else jax.lax.Precision.DEFAULT)
    return jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32,
                               precision=prec)


def _seg_kb_bounds(seg_vec, lo, hi, seq_len, block):
    """Block range [first, last) of positions in `seg_vec` ([seq_len]
    int32) whose id lies in [lo, hi] — packed-segment block skipping.
    Conservative-correct for ANY id layout: every exact match is inside
    the min/max positional envelope; non-matching positions inside it are
    killed by the in-tile equality mask."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, seq_len), 1)[0]
    valid = (seg_vec >= lo) & (seg_vec <= hi)
    first_pos = jnp.min(jnp.where(valid, iota, seq_len))
    last_pos = jnp.max(jnp.where(valid, iota, -1))
    return first_pos // block, (last_pos // block) + 1


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *refs, block_k, seq_k,
                      scale, causal, block_q, has_mask, has_lens,
                      has_segs=False, causal_offset=0):
    from jax.experimental import pallas as pl

    refs = list(refs)
    lens_ref = refs.pop(0) if has_lens else None
    mask_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None
    kseg_ref = refs.pop(0) if has_segs else None
    o_ref, lse_ref = refs
    qi = pl.program_id(2)
    q = q_ref[0, :, :]                              # [block_q, d], input dtype
    kv_len = lens_ref[0, 0] if has_lens else None
    q_seg = qseg_ref[0, :] if has_segs else None    # [block_q] int32

    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(kb * block_k, block_k), :]
        v = v_ref[0, pl.dslice(kb * block_k, block_k), :]
        s = _dot_f32(q, k, transpose_b=True) * scale   # [bq, bk] fp32
        if has_mask:
            s = s + mask_ref[0, 0, :, pl.dslice(kb * block_k, block_k)
                             ].astype(jnp.float32)
        if causal or has_lens:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        if causal:
            # cross-attention (sq != sk) aligns causally at the END:
            # query row i attends keys <= i + (sk - sq)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        if has_lens:
            s = jnp.where(k_pos < kv_len, s, _NEG_INF)
        if has_segs:
            k_seg = kseg_ref[0, pl.dslice(kb * block_k, block_k)]
            s = jnp.where(q_seg[:, None] == k_seg[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + _dot_f32(p.astype(v.dtype), v)
        return m_new, l_new, acc_new

    first_kb = 0
    if causal:
        # only key blocks up to (and including) the diagonal contribute
        last_kb = jnp.minimum(
            ((qi + 1) * block_q + causal_offset + block_k - 1) // block_k,
            num_kb)
    else:
        last_kb = num_kb
    if has_lens:
        # padded keys past kv_len never contribute — skip their blocks
        last_kb = jnp.minimum(last_kb, (kv_len + block_k - 1) // block_k)
    if has_segs:
        # packed segments: only key blocks overlapping this q block's
        # segment-id envelope contribute
        seg_first, seg_last = _seg_kb_bounds(
            kseg_ref[0, :], jnp.min(q_seg), jnp.max(q_seg), seq_k, block_k)
        first_kb = jnp.maximum(first_kb, seg_first)
        last_kb = jnp.minimum(last_kb, seg_last)
    m, l, acc = jax.lax.fori_loop(first_kb, last_kb, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, :, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # logsumexp per row — the backward kernels rebuild p = exp(s - lse).
    # lse lives as [BH, 1, S]; each program writes its q-block slice.
    lse_ref[0, 0, pl.dslice(qi * block_q, block_q)] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *refs, block_k, seq_k, scale, causal, block_q,
                         has_mask, has_lens, has_segs=False,
                         causal_offset=0):
    from jax.experimental import pallas as pl

    refs = list(refs)
    lens_ref = refs.pop(0) if has_lens else None
    mask_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None
    kseg_ref = refs.pop(0) if has_segs else None
    (dq_ref,) = refs
    qi = pl.program_id(2)
    q = q_ref[0, :, :]                            # [bq, d]
    do = do_ref[0, :, :]                          # [bq, d]
    lse = lse_ref[0, 0, pl.dslice(qi * block_q, block_q)]   # [bq]
    delta = delta_ref[0, 0, pl.dslice(qi * block_q, block_q)]
    kv_len = lens_ref[0, 0] if has_lens else None
    q_seg = qseg_ref[0, :] if has_segs else None
    num_kb = seq_k // block_k

    def body(kb, dq):
        k = k_ref[0, pl.dslice(kb * block_k, block_k), :]
        v = v_ref[0, pl.dslice(kb * block_k, block_k), :]
        s = _dot_f32(q, k, transpose_b=True) * scale
        if has_mask:
            s = s + mask_ref[0, 0, :, pl.dslice(kb * block_k, block_k)
                             ].astype(jnp.float32)
        if causal or has_lens:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        if has_lens:
            s = jnp.where(k_pos < kv_len, s, _NEG_INF)
        if has_segs:
            k_seg = kseg_ref[0, pl.dslice(kb * block_k, block_k)]
            s = jnp.where(q_seg[:, None] == k_seg[None, :], s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = _dot_f32(do, v, transpose_b=True)
        ds = p * (dp - delta[:, None])
        return dq + _dot_f32(ds.astype(k.dtype), k)

    first_kb = 0
    if causal:
        last_kb = jnp.minimum(
            ((qi + 1) * block_q + causal_offset + block_k - 1) // block_k,
            num_kb)
    else:
        last_kb = num_kb
    if has_lens:
        last_kb = jnp.minimum(last_kb, (kv_len + block_k - 1) // block_k)
    if has_segs:
        seg_first, seg_last = _seg_kb_bounds(
            kseg_ref[0, :], jnp.min(q_seg), jnp.max(q_seg), seq_k, block_k)
        first_kb = jnp.maximum(first_kb, seg_first)
        last_kb = jnp.minimum(last_kb, seg_last)
    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(first_kb, last_kb, body, dq)
    dq_ref[0, :, :] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *refs, block_q, seq_q, scale, causal, block_k,
                          has_mask, has_lens, has_segs=False,
                          causal_offset=0):
    from jax.experimental import pallas as pl

    refs = list(refs)
    lens_ref = refs.pop(0) if has_lens else None
    mask_ref = refs.pop(0) if has_mask else None
    qseg_ref = refs.pop(0) if has_segs else None   # [1, sq] full row
    kseg_ref = refs.pop(0) if has_segs else None   # [1, block_k] block
    dk_ref, dv_ref = refs
    ki = pl.program_id(2)
    k = k_ref[0, :, :]                            # [bk, d]
    v = v_ref[0, :, :]
    kv_len = lens_ref[0, 0] if has_lens else None
    k_seg = kseg_ref[0, :] if has_segs else None  # [bk]
    num_qb = seq_q // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(qb * block_q, block_q), :]
        do = do_ref[0, pl.dslice(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.dslice(qb * block_q, block_q)]
        s = _dot_f32(q, k, transpose_b=True) * scale   # [bq, bk]
        if has_mask:
            # mask block: [sq, block_k] column slice, sliced by q rows
            s = s + mask_ref[0, 0, pl.dslice(qb * block_q, block_q), :
                             ].astype(jnp.float32)
        if causal or has_lens:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos + causal_offset >= k_pos, s, _NEG_INF)
        if has_lens:
            s = jnp.where(k_pos < kv_len, s, _NEG_INF)
        if has_segs:
            q_seg = qseg_ref[0, pl.dslice(qb * block_q, block_q)]
            s = jnp.where(q_seg[:, None] == k_seg[None, :], s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        pb = p.astype(do.dtype)
        dv = dv + _dot_f32(pb.T, do)
        dp = _dot_f32(do, v, transpose_b=True)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk = dk + _dot_f32(ds.T, q)
        return dk, dv

    # causal: only q blocks at/after this k block's diagonal contribute
    if causal:
        first_qb = jnp.maximum(ki * block_k - causal_offset, 0) // block_q
    else:
        first_qb = 0
    last_qb = num_qb
    if has_segs:
        seg_first, seg_last = _seg_kb_bounds(
            qseg_ref[0, :], jnp.min(k_seg), jnp.max(k_seg), seq_q, block_q)
        first_qb = jnp.maximum(first_qb, seg_first)
        last_qb = jnp.minimum(last_qb, seg_last)
    dk = jnp.zeros((k.shape[0], k.shape[1]), jnp.float32)
    dv = jnp.zeros_like(dk)
    dk, dv = jax.lax.fori_loop(first_qb, last_qb, body, (dk, dv))
    dk_ref[0, :, :] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, :, :] = dv.astype(dv_ref.dtype)


def _largest_dividing_block(n, preferred=256, minimum=128):
    for b in (preferred, minimum):
        if n % b == 0:
            return min(b, n)
    return None


def _block_candidates(sq, sk):
    """Dividing (block_q, block_k) candidates, measured-best first.

    (512, 512) leads: on v5e at seq 1024 / d 64 it beat 256/256 by 15%
    and both XLA attention and the shipped jax flash kernel by ~2x (see
    BENCH_NOTES.md sweep); smaller geometries serve shorter sequences.
    """
    cands = []
    for bq, bk in ((512, 512), (1024, 1024), (512, 256), (256, 256),
                   (256, 128), (128, 128)):
        if sq % bq == 0 and sk % bk == 0 and (bq, bk) not in cands:
            cands.append((bq, bk))
    return cands or [(_largest_dividing_block(sq),
                      _largest_dividing_block(sk))]


# candidates are timed as an 8-deep chained jit so per-dispatch overhead
# (significant through a remote-chip tunnel) amortizes out of the signal
_TUNE_CHAIN = 8


def _run_fwd_candidate(bh, sq, sk, d, dtype, is_causal, scale, bq, bk):
    k = jnp.zeros((bh, sk, d), dtype)
    v = jnp.zeros((bh, sk, d), dtype)

    @jax.jit
    def chain(q):
        def body(q, _):
            o, _lse = _flash_fwd(q, k, v, is_causal, scale,
                                 block_q=bq, block_k=bk)
            return o, None
        out, _ = jax.lax.scan(body, q, length=_TUNE_CHAIN)
        return out

    return chain(jnp.zeros((bh, sq, d), dtype))


def _run_bwd_candidate(bh, sq, sk, d, dtype, is_causal, scale, bq, bk):
    k = jnp.zeros((bh, sk, d), dtype)
    v = jnp.zeros((bh, sk, d), dtype)
    out = jnp.zeros((bh, sq, d), dtype)
    lse = jnp.zeros((bh, 1, sq), jnp.float32)
    do = jnp.zeros((bh, sq, d), dtype)

    @jax.jit
    def chain(q):
        def body(q, _):
            dq, _dk, _dv = _flash_bwd(q, k, v, out, lse, do, is_causal,
                                      scale, block_q=bq, block_k=bk)
            return dq, None
        dq, _ = jax.lax.scan(body, q, length=_TUNE_CHAIN)
        return dq

    return chain(jnp.zeros((bh, sq, d), dtype))


_FLASH_RUNNERS = {"flash_fwd": _run_fwd_candidate,
                  "flash_bwd": _run_bwd_candidate}


def _tuned_blocks(kernel, sq, sk, d, bh, dtype, is_causal, scale):
    """Consult the autotune cache (ops/autotune.py) for block geometry.

    Default policy is the heuristic table in _block_candidates (seeded by
    the END-TO-END sweep in BENCH_NOTES.md): isolated kernel timing
    mispicks here — it measured 128/128 fastest in isolation while the
    full train step is 43% slower with it than with 512/512, because the
    surrounding XLA schedule (fusions and DMA overlap across the custom
    call boundary) dominates the isolated delta. Set PTPU_AUTOTUNE_SWEEP=1
    to measure anyway (useful on new chip generations to re-seed the
    table; phi autotune/auto_tune_base.h analog)."""
    import os

    from . import autotune as at

    # ptpu-check[host-sync]: autotune keys on static shape/dtype/flag
    # config — these are trace-time constants, not traced values
    key = (bh, sq, sk, d, str(dtype), bool(is_causal))
    cands = _block_candidates(sq, sk)
    runner = None
    if os.environ.get("PTPU_AUTOTUNE_SWEEP") == "1":
        def runner(cfg):
            bq, bk = cfg

            def go():
                return _FLASH_RUNNERS[kernel](bh, sq, sk, d, dtype,
                                              is_causal, scale, bq, bk)
            return go

    return at.autotune("pallas_" + kernel, key, cands, runner)


def _interpret() -> bool:
    # PTPU_PALLAS_INTERPRET=1 runs the kernels in pallas interpret mode so
    # the CPU test mesh can exercise them (parity tests without a chip)
    import os

    return os.environ.get("PTPU_PALLAS_INTERPRET") == "1"


def _flash_fwd(q, k, v, is_causal, scale, block_q=None, block_k=None,
               n_heads=1, mask=None, kv_lens=None, segments=None):
    """q,k,v: [BH, S, D] (heads folded into batch) → (out, lse).

    mask: optional additive [B, Hm, Sq, Sk] with Hm in {1, n_heads} —
    loaded blockwise via its own BlockSpec, so a per-batch mask (Hm=1) is
    never broadcast-materialized per head in HBM (the reference fuses the
    same way: fused_softmax_mask_op reads the unexpanded mask).
    kv_lens: optional [B, 1] int32 valid key lengths — the padded-batch
    fast path: keys at positions >= len are masked IN the kernel and their
    blocks never DMA'd, with no [Sq, Sk] mask in HBM at all."""
    from jax.experimental import pallas as pl

    bh, sq, d = q.shape
    sk = k.shape[1]
    if block_q is None or block_k is None:
        block_q, block_k = _tuned_blocks(
            "flash_fwd", sq, sk, d, bh, q.dtype, is_causal, scale)
    # blocks must tile the sequence exactly — remainder blocks would leave
    # output rows unwritten (gated by _pallas_ok, asserted here)
    block_q = _largest_dividing_block(sq, block_q)
    block_k = _largest_dividing_block(sk, block_k)
    assert block_q is not None and block_k is not None

    H = n_heads
    has_mask = mask is not None
    has_lens = kv_lens is not None
    has_segs = segments is not None
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        seq_k=sk,
        scale=scale,
        causal=is_causal,
        block_q=block_q,
        has_mask=has_mask,
        has_lens=has_lens,
        has_segs=has_segs,
        causal_offset=sk - sq,
    )
    grid = (bh // H, H, sq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, h, i: (b * H + h, i, 0)),
        pl.BlockSpec((1, sk, d), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda b, h, i: (b * H + h, 0, 0)),
    ]
    args = [q, k, v]
    if has_lens:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, h, i: (b, 0)))
        args.append(kv_lens)
    if has_mask:
        bm, hm = mask.shape[0], mask.shape[1]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_q, sk),
            lambda b, h, i: (b if bm > 1 else 0, h if hm > 1 else 0, i, 0)))
        args.append(mask)
    if has_segs:
        # segments: [B, S] int32 shared by q and k (packed self-attention)
        in_specs.append(pl.BlockSpec((1, block_q),
                                     lambda b, h, i: (b, i)))       # q block
        in_specs.append(pl.BlockSpec((1, sk),
                                     lambda b, h, i: (b, 0)))       # k row
        args.extend([segments, segments])
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, h, i: (b * H + h, i, 0)),
            pl.BlockSpec((1, 1, sq), lambda b, h, i: (b * H + h, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)


def _flash_bwd(q, k, v, out, lse, do, is_causal, scale,
               block_q=None, block_k=None, n_heads=1, mask=None,
               kv_lens=None, segments=None):
    """Blockwise flash backward: recomputes p per tile from (q,k,lse) —
    no S^2 materialization in HBM. Returns (dq, dk, dv), all [BH, S, D]."""
    from jax.experimental import pallas as pl

    bh, sq, d = q.shape
    sk = k.shape[1]
    if block_q is None or block_k is None:
        block_q, block_k = _tuned_blocks(
            "flash_bwd", sq, sk, d, bh, q.dtype, is_causal, scale)
    block_q = _largest_dividing_block(sq, block_q)
    block_k = _largest_dividing_block(sk, block_k)
    assert block_q is not None and block_k is not None

    H = n_heads
    has_mask = mask is not None
    has_lens = kv_lens is not None
    has_segs = segments is not None
    bm = mask.shape[0] if has_mask else 1
    hm = mask.shape[1] if has_mask else 1
    interp = _interpret()

    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)[:, None, :]  # [bh,1,sq]

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, h, i: (b * H + h, i, 0)),
        pl.BlockSpec((1, sk, d), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, h, i: (b * H + h, i, 0)),
        pl.BlockSpec((1, 1, sq), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, 1, sq), lambda b, h, i: (b * H + h, 0, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if has_lens:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, h, i: (b, 0)))
        args.append(kv_lens)
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, block_q, sk),
            lambda b, h, i: (b if bm > 1 else 0, h if hm > 1 else 0, i, 0)))
        args.append(mask)
    if has_segs:
        in_specs.append(pl.BlockSpec((1, block_q), lambda b, h, i: (b, i)))
        in_specs.append(pl.BlockSpec((1, sk), lambda b, h, i: (b, 0)))
        args.extend([segments, segments])
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, seq_k=sk,
                          scale=scale, causal=is_causal, block_q=block_q,
                          has_mask=has_mask, has_lens=has_lens,
                          has_segs=has_segs,
                          causal_offset=sk - sq),
        grid=(bh // H, H, sq // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, h, i: (b * H + h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interp,
    )(*args)

    in_specs = [
        pl.BlockSpec((1, sq, d), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, h, i: (b * H + h, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, h, i: (b * H + h, i, 0)),
        pl.BlockSpec((1, sq, d), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, 1, sq), lambda b, h, i: (b * H + h, 0, 0)),
        pl.BlockSpec((1, 1, sq), lambda b, h, i: (b * H + h, 0, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if has_lens:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, h, i: (b, 0)))
        args.append(kv_lens)
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, sq, block_k),
            lambda b, h, i: (b if bm > 1 else 0, h if hm > 1 else 0, 0, i)))
        args.append(mask)
    if has_segs:
        in_specs.append(pl.BlockSpec((1, sq), lambda b, h, i: (b, 0)))
        in_specs.append(pl.BlockSpec((1, block_k), lambda b, h, i: (b, i)))
        args.extend([segments, segments])
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, seq_q=sq,
                          scale=scale, causal=is_causal, block_k=block_k,
                          has_mask=has_mask, has_lens=has_lens,
                          has_segs=has_segs,
                          causal_offset=sk - sq),
        grid=(bh // H, H, sk // block_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, h, i: (b * H + h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, h, i: (b * H + h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interp,
    )(*args)
    return dq, dk, dv


def _mask_shape_ok(mask, B, H, sq, sk) -> bool:
    shp = mask.shape
    if len(shp) == 2:
        shp = (1, 1) + shp
    elif len(shp) == 3:
        shp = (shp[0], 1) + shp[1:]
    if len(shp) != 4:
        return False
    bm, hm, mq, mk = shp
    return (mq, mk) == (sq, sk) and bm in (1, B) and hm in (1, H)


def _pallas_ok(q, k, is_causal, mask, kv_lens=None, segment_ids=None) -> bool:
    if not (_on_tpu() or _interpret()):
        _count_path("attn_fallback:off_tpu")
        return False
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if d % 128 != 0 and d not in (64, 128, 256):
        _count_path("attn_fallback:head_dim")
        return False
    if _largest_dividing_block(sq) is None or _largest_dividing_block(sk) is None:
        _count_path("attn_fallback:seq_not_128_multiple")
        return False
    if mask is not None and not _mask_shape_ok(mask, b, h, sq, sk):
        _count_path("attn_fallback:mask_shape")
        return False
    if kv_lens is not None and tuple(kv_lens.shape) != (b,):
        _count_path("attn_fallback:kv_lens_shape")
        return False
    # (segment_ids shape is validated with a raise at the public entry —
    # flash_attention_arrays — since no dense fallback can serve a bad
    # shape either; no check here)
    if is_causal and sk - sq < 0:
        # causal with more queries than keys has no standard alignment
        _count_path("attn_fallback:causal_sq_gt_sk")
        return False
    return True


def _fold_heads(x):
    b, s, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)


def _unfold_heads(x, b, h):
    bh, s, d = x.shape
    return jnp.moveaxis(x.reshape(b, h, s, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _flash_attn_core(q, k, v, mask, kv_lens, segs, is_causal, scale,
                     use_pallas):
    if use_pallas:
        b, s, h, d = q.shape
        of, _ = _flash_fwd(_fold_heads(q), _fold_heads(k), _fold_heads(v),
                           is_causal, scale, n_heads=h, mask=mask,
                           kv_lens=kv_lens, segments=segs)
        return _unfold_heads(of, b, h)
    return mha_reference(q, k, v, mask, is_causal, scale,
                         kv_lens=None if kv_lens is None else kv_lens[:, 0],
                         segment_ids=segs)


def _flash_attn_fwd(q, k, v, mask, kv_lens, segs, is_causal, scale,
                    use_pallas):
    if use_pallas:
        b, s, h, d = q.shape
        qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
        of, lse = _flash_fwd(qf, kf, vf, is_causal, scale, n_heads=h,
                             mask=mask, kv_lens=kv_lens, segments=segs)
        return _unfold_heads(of, b, h), (qf, kf, vf, of, lse, mask,
                                         kv_lens, segs, (b, h))
    out = mha_reference(q, k, v, mask, is_causal, scale,
                        kv_lens=None if kv_lens is None else kv_lens[:, 0],
                        segment_ids=segs)
    return out, (q, k, v, None, None, mask, kv_lens, segs, None)


def _flash_attn_bwd(is_causal, scale, use_pallas, res, g):
    q, k, v, out, lse, mask, kv_lens, segs, bh_shape = res
    # mask is additive: its cotangent exists but no caller consumes it
    dmask = None if mask is None else jnp.zeros_like(mask)
    dlens = (None if kv_lens is None
             else np.zeros(kv_lens.shape, jax.dtypes.float0))
    dsegs = (None if segs is None
             else np.zeros(segs.shape, jax.dtypes.float0))
    if use_pallas:
        b, h = bh_shape
        dq, dk, dv = _flash_bwd(q, k, v, out, lse, _fold_heads(g),
                                is_causal, scale, n_heads=h, mask=mask,
                                kv_lens=kv_lens, segments=segs)
        return (_unfold_heads(dq, b, h), _unfold_heads(dk, b, h),
                _unfold_heads(dv, b, h), dmask, dlens, dsegs)
    # XLA fallback: recompute-based backward through the reference
    _, vjp_fn = jax.vjp(
        lambda a, b, c: mha_reference(
            a, b, c, mask, is_causal, scale,
            kv_lens=None if kv_lens is None else kv_lens[:, 0],
            segment_ids=segs),
        q, k, v)
    return vjp_fn(g) + (dmask, dlens, dsegs)


_flash_attn_core.defvjp(_flash_attn_fwd, _flash_attn_bwd)


def _normalize_mask(attn_mask):
    """Bring a (shape-validated) user mask to additive [Bm, Hm, Sq, Sk]
    without broadcasting it out in HBM."""
    m = attn_mask
    if m.ndim == 2:
        m = m[None, None]
    elif m.ndim == 3:
        m = m[:, None]
    if m.dtype == jnp.bool_:
        m = jnp.where(m, jnp.float32(0), jnp.float32(_NEG_INF_MASK))
    return m


_NEG_INF_MASK = -1e30


def flash_attention_arrays(q, k, v, attn_mask=None, is_causal=False,
                           scale=None, kv_lens=None, segment_ids=None):
    """Array-level entry (used inside compiled training steps).

    attn_mask on the KERNEL path is treated as a CONSTANT (stop_gradient):
    a flash kernel never materializes the [Sq, Sk] probability tile in HBM,
    so a mask cotangent would cost the O(S^2) write the kernel exists to
    avoid — the same contract as the reference's fused attention
    (fused_gate_attention does not emit a mask grad). Learned additive
    biases that need gradients should use `mha_reference` (or shapes that
    fall back to it), where the full vjp applies.

    kv_lens: optional [B] int32 per-sequence valid KEY length (>= 1) for
    right-padded variable-length batches — keeps the kernel path with NO
    [B,H,S,S] mask in HBM (the padded key blocks are never even DMA'd).
    Composable with is_causal and attn_mask.

    segment_ids: optional [B, S] int32 packed-sequence ids (the standard
    TPU pretraining input: multiple documents per row) — self-attention
    only; positions attend iff ids match, composed with is_causal. The
    kernel masks in-tile and SKIPS key blocks outside each q block's
    segment envelope, so packed batches keep flash cost with no [S, S]
    mask in HBM. Rows with an id that appears nowhere else (e.g. padding)
    produce unspecified output at those positions — ignore them, as with
    any padded attention. (SURVEY declares this capability class native —
    the reference has no flash kernels at all; analog masking semantics:
    praxis/flax segment_ids.)
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    lens = None
    if kv_lens is not None:
        lens = jax.lax.stop_gradient(
            jnp.asarray(kv_lens, jnp.int32).reshape(-1, 1))
    segs = None
    if segment_ids is not None:
        segs = jax.lax.stop_gradient(jnp.asarray(segment_ids, jnp.int32))
        b, sq, sk = q.shape[0], q.shape[1], k.shape[1]
        if sq != sk or tuple(segs.shape) != (b, sq):
            # no dense fallback exists either (segment attention is
            # self-attention with one [B, S] id array) — user error
            raise ValueError(
                f"segment_ids must be [batch, seq] = [{b}, {sq}] for "
                f"self-attention (got shape {tuple(segs.shape)}, "
                f"key length {sk})")
    if _pallas_ok(q, k, is_causal, attn_mask,
                  None if lens is None else lens[:, 0], segs):
        _count_path("attn_kernel" + (":kv_lens" if lens is not None else "")
                    + (":segs" if segs is not None else "")
                    + (":causal_cross" if is_causal
                       and q.shape[1] != k.shape[1] else ""))
        mask = None
        if attn_mask is not None:
            mask = jax.lax.stop_gradient(_normalize_mask(attn_mask))
        return _flash_attn_core(q, k, v, mask, lens, segs, is_causal, scale,
                                True)
    return mha_reference(q, k, v, attn_mask, is_causal, scale,
                         kv_lens=None if lens is None else lens[:, 0],
                         segment_ids=segs)


def cached_attention_arrays(q, k, v, k_cache, v_cache, t, scale=None,
                            mask=None):
    """KV-cache attention for autoregressive decoding (reference CacheKV
    semantics: fused_multi_transformer_op.cu:90 — the fused op's cache_kv
    holds past keys/values and the new token is written at `time_step`).

    q, k, v:            [B, S, H, D] — the current chunk (S = prompt length
                        at prefill, 1 per decode step)
    k_cache, v_cache:   flat [B, S_max, H*D] rings (preferred — see the
                        layout note in the body) or legacy [B, S_max, H, D];
                        static shapes mean ONE XLA executable serves every
                        decode position (dynamic start index via
                        lax.dynamic_update_slice)
    t:                  int32 scalar — write position of the chunk's first
                        token (0 at prefill, current length during decode)
    mask:               optional extra mask over cache positions,
                        broadcastable to [B, H, S, S_max] — bool (True =
                        attend) or additive float; combined with the causal
                        mask (use for padded-prompt batches)

    Returns (out [B,S,H,D], new_k_cache, new_v_cache). Attention is causal
    over cache positions <= each query's absolute position; the O(S_max)
    masked-softmax XLA path is bandwidth-bound (MXU irrelevant at S_q=1),
    so no Pallas kernel is needed for correctness-first decode.
    """
    b, s, h, d = q.shape
    s_max = k_cache.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    t = jnp.asarray(t, jnp.int32)
    # caches may be [B, Smax, H, D] or flattened [B, Smax, H*D]. The flat
    # form is what decode wants: the (H, D) split never reaches any
    # buffer, so XLA has no reason to pick an (H, D)-tiled cache layout
    # that would force per-step relayout copies around the Pallas kernel
    # (whose view is flat anyway), and the one-row DUS write stays
    # contiguous.
    flat = k_cache.ndim == 3
    if flat:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.reshape(b, s, h * d).astype(k_cache.dtype), (0, t, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.reshape(b, s, h * d).astype(v_cache.dtype), (0, t, 0))
    else:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, t, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, t, 0, 0))
    if mask is None and _decode_ok(q, k_cache, v_cache):
        # S_q=1 decode: Pallas kernel reads only the valid cache prefix
        out = flash_decode_arrays(q, k_cache, v_cache, t + 1, scale=scale)
        return out.astype(q.dtype), k_cache, v_cache
    kc4 = k_cache.reshape(b, s_max, h, d) if flat else k_cache
    vc4 = v_cache.reshape(b, s_max, h, d) if flat else v_cache
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc4,
                        preferred_element_type=jnp.float32) * scale
    q_pos = t + jnp.arange(s, dtype=jnp.int32)          # absolute positions
    k_pos = jnp.arange(s_max, dtype=jnp.int32)
    causal = k_pos[None, :] <= q_pos[:, None]           # [S, S_max] causal
    logits = jnp.where(causal[None, None], logits, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, _NEG_INF)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vc4.dtype), vc4)
    return out.astype(q.dtype), k_cache, v_cache


def flash_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
    training=True, name=None, segment_ids=None
):
    """Tensor-level fused attention (nn.functional.scaled_dot_product_attention).
    segment_ids: optional [B, S] int ids for packed-sequence batches (see
    flash_attention_arrays)."""
    mask_arr = None
    if attn_mask is not None:
        mask_arr = attn_mask._data if isinstance(attn_mask, Tensor) else jnp.asarray(attn_mask)
    seg_arr = None
    if segment_ids is not None:
        seg_arr = (segment_ids._data if isinstance(segment_ids, Tensor)
                   else jnp.asarray(segment_ids))

    drop_key = _rng.next_key() if (dropout_p > 0.0 and training) else None

    def fn(q, k, v):
        out = flash_attention_arrays(q, k, v, mask_arr, is_causal,
                                     segment_ids=seg_arr)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_p), 0.0).astype(out.dtype)
        return out

    return apply(fn, query, key, value, name="flash_attention")


# ---------------------------------------------------------------------------
# Flash-decode kernel: single-token attention against a KV cache
# ---------------------------------------------------------------------------

def _decode_seg_helpers(h, d, fast):
    """Head-segmented matmul machinery shared by the decode kernels:
    Mosaic's (8,128) tiling forbids slicing H or D when they aren't tile
    multiples, so per-head logits come from one MXU matmul against the
    segment indicator (s = (K ∘ q) @ seg, [rows, H*D] @ [H*D, H]) and
    per-head weights expand back to lanes with its swapped twin. Both are
    built straight from 2D iotas (Mosaic cannot legalize transposes of
    these skinny shapes)."""
    hd = h * d
    seg = (jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
           == jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
           ).astype(fast)                                       # [hd, h]
    expand = (jax.lax.broadcasted_iota(jnp.int32, (h, hd), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (h, hd), 1) // d
              ).astype(fast)                                    # [h, hd]

    def seg_dot(a3, mat, exact=False):
        """[bb, bk, X] @ [X, Y] -> [bb, bk, Y] via a free row-merge
        reshape. Default: operands in the cache's compute dtype (bf16
        caches → MXU fast path with fp32 accum, flash-standard for the
        big K/p products). exact=True keeps fp32 operands (HIGHEST) —
        required for the alpha/l rescale expansions, where low-precision
        rounding would compound across blocks."""
        rows = a3.shape[0] * a3.shape[1]
        a2 = a3.reshape(rows, a3.shape[2])
        if exact:
            out = _dot_f32(a2, mat.astype(jnp.float32))
        else:
            out = _dot_f32(a2.astype(fast), mat)
        return out.reshape(a3.shape[0], a3.shape[1], mat.shape[1])

    return seg, expand, seg_dot


def _prefix_attn_loop(qf, length, num_kb, row0, k_hbm, v_hbm, k_buf, v_buf,
                      sem, seg, expand, seg_dot, *, bb, block_k, h, scale,
                      mask_all=None):
    """Double-buffered online-softmax attention of qf [bb, 1, H*D] (fp32)
    against cache rows [row0:row0+bb, 0:length) streamed from HBM —
    the shared core of _decode_kernel and _fused_decode_layer_kernel.
    Returns the running (m, l, acc) softmax state ([bb,1,H] / [bb,1,H*D]
    fp32) so callers can fold in further terms before normalizing."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    hd = qf.shape[-1]

    def copies(slot, kb):
        start = kb * block_k
        src_k = k_hbm.at[pl.ds(row0, bb), pl.ds(start, block_k)]
        src_v = v_hbm.at[pl.ds(row0, bb), pl.ds(start, block_k)]
        return (pltpu.make_async_copy(src_k, k_buf.at[slot], sem.at[slot, 0]),
                pltpu.make_async_copy(src_v, v_buf.at[slot], sem.at[slot, 1]))

    for c in copies(0, 0):
        c.start()

    def body(kb, carry):
        m, l, acc = carry          # m,l: [bb,1,H]; acc: [bb,1,H*D] fp32
        slot = jax.lax.rem(kb, 2)
        start = kb * block_k

        @pl.when(kb + 1 < num_kb)
        def _prefetch():
            for c in copies(1 - slot, kb + 1):
                c.start()

        kd, vd = copies(slot, kb)
        kd.wait()
        kf = k_buf[slot].astype(jnp.float32)                     # [bb,bk,hd]
        s = seg_dot(kf * qf, seg) * scale                        # [bb,bk,H]
        if mask_all is not None:
            # additive row mask over cache positions (padded batches);
            # rows address the caller's batch slab, like the cache DMAs
            s = s + jax.lax.dynamic_slice(
                mask_all, (row0, start), (bb, block_k))[:, :, None]
        pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (bb, block_k, h), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                                   # [bb,bk,H]
        alpha = jnp.exp(m - m_new)                               # [bb,1,H]
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        vd.wait()
        vf = v_buf[slot].astype(jnp.float32)                     # [bb,bk,hd]
        pexp = seg_dot(p, expand)                                # [bb,bk,hd]
        pv = jnp.sum(pexp * vf, axis=1, keepdims=True)           # [bb,1,hd]
        acc_new = acc * seg_dot(alpha, expand, exact=True) + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((bb, 1, h), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bb, 1, h), jnp.float32)
    acc0 = jnp.zeros((bb, 1, hd), jnp.float32)
    return jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))


def _decode_kernel(len_ref, q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sem,
                   *, block_b, block_k, h, d, scale):
    """One program per batch slab: q [bb, 1, H*D] against the valid prefix
    of the caches [B, S_max, H*D] living in HBM. The valid length arrives
    via scalar prefetch (len_ref), so only ceil(len / block_k) cache
    blocks are ever DMA'd into VMEM — the XLA fallback reads (and masks)
    all S_max positions — and consecutive blocks are double-buffered so
    the next slab's DMA overlaps the current block's math. Heads live
    flattened in the lane dim: Mosaic's (8,128) tiling forbids slicing H
    or D when they aren't tile multiples, so per-head logits come from one
    MXU matmul against the segment indicator (s = (K ∘ q) @ seg,
    [bb*bk, H*D] @ [H*D, H]) and the per-head softmax weights are expanded
    back to lanes with its swapped twin (p @ E, [bb*bk, H] @ [H, H*D]).
    Online softmax over blocks, fp32 accumulation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ib = pl.program_id(0)
    length = len_ref[0]
    # clamp to >= 1 block: the pre-loop prefetch below starts a DMA
    # unconditionally, and a zero-trip loop would never wait on it
    # (unbalanced semaphore at kernel exit); length 0 just reads garbage
    # that the position mask then fully excludes... except nothing is
    # valid — callers pass t+1 >= 1, and the mask yields uniform weights
    # over block 0 in the degenerate case rather than a fault.
    num_kb = jnp.maximum((length + block_k - 1) // block_k, 1)
    bb = block_b
    qf = q_ref[...].astype(jnp.float32)                          # [bb,1,hd]
    # _dot_f32 contract: bf16 caches ride the MXU's fast path (flash-
    # standard), fp32 caches keep fp32-HIGHEST correctness
    fast = jnp.bfloat16 if k_buf.dtype == jnp.bfloat16 else jnp.float32
    seg, expand, seg_dot = _decode_seg_helpers(h, d, fast)
    m, l, acc = _prefix_attn_loop(
        qf, length, num_kb, ib * bb, k_hbm, v_hbm, k_buf, v_buf, sem,
        seg, expand, seg_dot, bb=bb, block_k=block_k, h=h, scale=scale)
    l_exp = seg_dot(l, expand, exact=True)                       # [bb,1,hd]
    o_ref[...] = (acc / jnp.maximum(l_exp, 1e-30)).astype(o_ref.dtype)


def flash_decode_arrays(q, k_cache, v_cache, length, scale=None,
                        block_k=256):
    """Decode-attention against the first `length` cache positions.

    q [B, 1, H, D]; k_cache/v_cache [B, S_max, H, D]; length: int32 scalar
    (t + 1 during decode). Returns [B, 1, H, D]. The TPU answer to the
    reference's masked full-cache attention inside
    fused_multi_transformer_op.cu's decode branch: at S_q = 1 the MXU is
    idle and HBM bandwidth on cache reads is everything, so the kernel
    reads only the valid cache prefix (blockwise DMA, online softmax)
    instead of all S_max rows."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    s_max = k_cache.shape[1]
    assert s == 1, "flash_decode_arrays is the S_q=1 path"
    # length is traced, so the >=1 contract can't be asserted here; the
    # kernel clamps num_kb to 1 block instead — an unmatched pre-loop DMA
    # start (never waited) would leave a non-zero semaphore at kernel exit
    if k_cache.ndim == 4:               # [B, Smax, H, D] → flat lane view
        k_cache = k_cache.reshape(b, s_max, h * d)
        v_cache = v_cache.reshape(b, s_max, h * d)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # blocks must tile s_max exactly: the DMA loop reads whole blocks, and a
    # ragged final block would read past the cache rows
    block_k = min(block_k, s_max)
    while s_max % block_k:
        block_k //= 2
    # prefer >= 2 seq blocks so the double-buffered DMA actually overlaps
    if s_max // block_k < 2 and block_k >= 16 and s_max % (block_k // 2) == 0:
        block_k //= 2
    # batch slab: largest divisor of B whose double-buffered k+v slabs
    # ([2, bb, block_k, H*D] each) stay within ~8 MiB of VMEM; keep
    # block_k a sublane multiple so the seq-slice DMA stays tile-aligned
    itemsize = jnp.dtype(k_cache.dtype).itemsize
    block_b = b
    while block_b > 1 and (b % block_b
                           or 4 * block_b * block_k * h * d * itemsize
                           > 8 * 2**20):
        block_b -= 1
    while (block_k > 8
           and 4 * block_b * block_k * h * d * itemsize > 8 * 2**20):
        block_k //= 2
    assert block_k % 8 == 0 or block_k == s_max

    # One program per batch slab. Heads are flattened into the lane dim
    # ([B, S, H*D] views — free reshapes of trailing contiguous dims): the
    # cache DMA then slices only untiled/aligned dims, and q/o blocks'
    # last two dims (1, H*D) equal the array dims — Mosaic requires
    # blocks' last two dims be (8,128)-divisible OR full, and forbids
    # slicing H or D when they aren't tile multiples (interpret mode
    # never checks this).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b // block_b,),
        in_specs=[
            pl.BlockSpec((block_b, 1, h * d), lambda i, len_ref: (i, 0, 0)),
            # pin caches to HBM: under ANY, Mosaic may place them in VMEM
            # and the kernel's whole point is NOT streaming them there.
            # (pltpu.HBM is a newer-jax name; 0.4.x only has ANY, where
            # caches bigger than VMEM land in HBM regardless — and this
            # host runs the kernel in interpret mode anyway)
            pl.BlockSpec(memory_space=getattr(pltpu, "HBM", pltpu.ANY)),
            pl.BlockSpec(memory_space=getattr(pltpu, "HBM", pltpu.ANY)),
        ],
        out_specs=pl.BlockSpec((block_b, 1, h * d),
                               lambda i, len_ref: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block_b, block_k, h * d), k_cache.dtype),
            pltpu.VMEM((2, block_b, block_k, h * d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(_decode_kernel, block_b=block_b,
                               block_k=block_k, h=h, d=d, scale=scale)
    lengths = jnp.asarray(length, jnp.int32).reshape(1)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, h * d), q.dtype),
        interpret=_interpret(),
    )(lengths, q.reshape(b, 1, h * d), k_cache, v_cache)
    return out.reshape(b, 1, h, d)


def _decode_ok(q, k_cache, v_cache) -> bool:
    import os
    forced = os.environ.get("PTPU_FLASH_DECODE")
    if forced == "0":
        _count_path("decode_fallback:disabled")
        return False
    if not (_on_tpu() or _interpret()):
        _count_path("decode_fallback:off_tpu")
        return False
    b, s, h, d = q.shape
    s_max = k_cache.shape[1]
    if s != 1:
        _count_path("decode_fallback:chunk_gt_1")
        return False
    if d not in (64, 128, 256) or (h * d) % 128 != 0:
        _count_path("decode_fallback:head_geometry")
        return False
    if s_max % 128 != 0:
        _count_path("decode_fallback:smax_not_128_multiple")
        return False
    # same-dtype: the kernel's lax.dot_general needs matching operands (the
    # XLA fallback einsum would promote mixed fp32-q/bf16-cache instead)
    if not (q.dtype == k_cache.dtype == v_cache.dtype):
        _count_path("decode_fallback:dtype_mix")
        return False
    if forced != "1":
        # auto policy (checked LAST so counter attribution stays honest):
        # at short caches the kernel's fixed costs (launch, DMA double-
        # buffer priming) dominate the tiny prefix read and the XLA
        # masked full-cache path wins (round-2 bisect: ~0.23 ms/layer at
        # S_max=256 vs a ~0.02 ms bound); prefix-skipping pays off once
        # the cache is long. PTPU_FLASH_DECODE=1/0 forces either way.
        try:
            min_smax = int(
                os.environ.get("PTPU_FLASH_DECODE_MIN_SMAX", "1024"))
        except ValueError:
            min_smax = 1024
        if s_max < min_smax:
            _count_path("decode_fallback:small_smax")
            return False
    _count_path("decode_kernel")
    return True


# ---------------------------------------------------------------------------
# Fused per-layer decode step (reference:
# fused_multi_transformer_op.cu:90 — one CUDA op runs a whole layer's
# decode: LN, qkv, cache write, attention, out-proj. The round-2 bisect
# attributed the decode gap to kernel-LAUNCH count (~100-200 kernels/token
# step at 124M ≈ 1-3 ms of fixed cost), so the TPU answer is the same
# shape: ONE Pallas program per layer per token step.)
# ---------------------------------------------------------------------------

def _fused_decode_layer_kernel(len_ref, x_ref, lnw_ref, lnb_ref,
                               wqkv_ref, bqkv_ref, wo_ref, bo_ref,
                               k_in, v_in, *refs,
                               block_k, h, d, eps, scale, has_mask):
    """Single program: x [B, H*D] residual stream in, y = x + attn_out
    out; the new token's k/v are written in place into the HBM cache rings
    (k_out/v_out alias k_in/v_in). Prefix length t arrives via scalar
    prefetch; the current token's k/v never round-trip through HBM — the
    self-attention term folds into the online softmax from registers.
    Requires t >= 1 (decode always follows a prefill). has_mask adds an
    additive [B, S_max] row mask over prefix positions (padded-prompt
    batches: -inf at pad slots; the current token is always valid)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    refs = list(refs)
    mask_ref = refs.pop(0) if has_mask else None
    y_ref, k_out, v_out, kv_stage, k_buf, v_buf, sem, wsem = refs
    t = len_ref[0]                          # prefix length == write row
    bb = x_ref.shape[0]
    hd = h * d

    # LN1 (fp32 row stats)
    x32 = x_ref[...].astype(jnp.float32)                     # [B, hd]
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mu
    rs = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    xn = (xc * rs * lnw_ref[...].astype(jnp.float32)[None, :]
          + lnb_ref[...].astype(jnp.float32)[None, :])

    fast = jnp.bfloat16 if k_buf.dtype == jnp.bfloat16 else jnp.float32
    qkv = _dot_f32(xn.astype(fast), wqkv_ref[...]) \
        + bqkv_ref[...].astype(jnp.float32)[None, :]         # [B, 3hd] f32
    q = qkv[:, :hd]
    k_new = qkv[:, hd:2 * hd]
    v_new = qkv[:, 2 * hd:]
    qf = q[:, None, :]                                       # [B, 1, hd]

    seg, expand, seg_dot = _decode_seg_helpers(h, d, fast)
    num_kb = jnp.maximum((t + block_k - 1) // block_k, 1)
    mask_all = mask_ref[...].astype(jnp.float32) if has_mask else None
    m, l, acc = _prefix_attn_loop(
        qf, t, num_kb, 0, k_in, v_in, k_buf, v_buf, sem,
        seg, expand, seg_dot, bb=bb, block_k=block_k, h=h, scale=scale,
        mask_all=mask_all)

    # current token's self-attention term, straight from registers
    s_self = seg_dot(k_new[:, None, :] * qf, seg) * scale    # [B, 1, h]
    m2 = jnp.maximum(m, s_self)
    p_self = jnp.exp(s_self - m2)
    alpha = jnp.exp(m - m2)
    l = alpha * l + p_self
    acc = (acc * seg_dot(alpha, expand, exact=True)
           + seg_dot(p_self, expand) * v_new[:, None, :])

    # cache write AFTER the prefix loop (no read/write overlap on the
    # aliased ring) — the tiny one-row DMAs overlap the out-proj matmul
    kv_stage[0] = k_new[:, None, :].astype(kv_stage.dtype)
    kv_stage[1] = v_new[:, None, :].astype(kv_stage.dtype)
    wk = pltpu.make_async_copy(
        kv_stage.at[0], k_out.at[pl.ds(0, bb), pl.ds(t, 1)], wsem.at[0])
    wv = pltpu.make_async_copy(
        kv_stage.at[1], v_out.at[pl.ds(0, bb), pl.ds(t, 1)], wsem.at[1])
    wk.start()
    wv.start()

    l_exp = seg_dot(l, expand, exact=True)                   # [B, 1, hd]
    attn = (acc / jnp.maximum(l_exp, 1e-30))[:, 0, :]        # [B, hd] f32
    proj = _dot_f32(attn.astype(fast), wo_ref[...]) \
        + bo_ref[...].astype(jnp.float32)[None, :]
    y_ref[...] = (x32 + proj).astype(y_ref.dtype)
    wk.wait()
    wv.wait()


def fused_decode_layer_arrays(x, ln_w, ln_b, wqkv, bqkv, wo, bo,
                              k_cache, v_cache, t, n_heads, eps=1e-5,
                              scale=None, block_k=256, cache_mask=None):
    """One transformer layer's decode step (S_q = 1) in ONE Pallas call:
    LN -> qkv -> ring cache write (in place, aliased) -> online-softmax
    attention over the valid prefix + the current token -> out-proj ->
    residual add. x: [B, H*D]; caches: flat [B, S_max, H*D] rings;
    t: int32 scalar prefix length (>= 1). cache_mask: optional additive
    [B, S_max] (or [B, 1, 1, S_max]) row mask over cache positions —
    padded-prompt batches keep the fused path. Returns
    (y, k_cache, v_cache) with the caches updated in place (buffers
    donated)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hd = x.shape
    h = n_heads
    d = hd // h
    s_max = k_cache.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_k = min(block_k, s_max)
    while s_max % block_k:
        block_k //= 2
    itemsize = jnp.dtype(k_cache.dtype).itemsize
    # shrink the streamed cache blocks until the double-buffered slabs
    # plus resident weights fit the VMEM budget
    weights_bytes = (hd * 3 * hd + hd * hd) * jnp.dtype(wqkv.dtype).itemsize
    if cache_mask is not None:
        weights_bytes += b * s_max * 4      # resident fp32 row mask block
    while (block_k > 8
           and 4 * b * block_k * hd * itemsize > 10 * 2**20 - weights_bytes):
        block_k //= 2

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((b, hd), lambda i, len_ref: (0, 0)),          # x
            pl.BlockSpec((hd,), lambda i, len_ref: (0,)),              # ln_w
            pl.BlockSpec((hd,), lambda i, len_ref: (0,)),              # ln_b
            pl.BlockSpec((hd, 3 * hd), lambda i, len_ref: (0, 0)),     # wqkv
            pl.BlockSpec((3 * hd,), lambda i, len_ref: (0,)),          # bqkv
            pl.BlockSpec((hd, hd), lambda i, len_ref: (0, 0)),         # wo
            pl.BlockSpec((hd,), lambda i, len_ref: (0,)),              # bo
            pl.BlockSpec(memory_space=pltpu.ANY),                      # k_in
            pl.BlockSpec(memory_space=pltpu.ANY),                      # v_in
        ] + ([pl.BlockSpec((b, s_max), lambda i, len_ref: (0, 0))]
             if cache_mask is not None else []),                       # mask
        out_specs=[
            pl.BlockSpec((b, hd), lambda i, len_ref: (0, 0)),          # y
            pl.BlockSpec(memory_space=pltpu.ANY),                      # k_out
            pl.BlockSpec(memory_space=pltpu.ANY),                      # v_out
        ],
        scratch_shapes=[
            pltpu.VMEM((2, b, 1, hd), k_cache.dtype),                  # stage
            pltpu.VMEM((2, b, block_k, hd), k_cache.dtype),
            pltpu.VMEM((2, b, block_k, hd), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(_fused_decode_layer_kernel, block_k=block_k,
                               h=h, d=d, eps=float(eps), scale=scale,
                               has_mask=cache_mask is not None)
    lengths = jnp.asarray(t, jnp.int32).reshape(1)
    mask_args = []
    if cache_mask is not None:
        mask_args = [jnp.asarray(cache_mask, jnp.float32
                                 ).reshape(b, s_max)]
    # aliasing: inputs are indexed INCLUDING the scalar-prefetch arg
    # (lengths=0, x=1, ..., k_in=8, v_in=9; mask, when present, is 10);
    # outputs (y=0, k=1, v=2)
    y, k2, v2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hd), x.dtype),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        input_output_aliases={8: 1, 9: 2},
        interpret=_interpret(),
    )(lengths, x, ln_w, ln_b, wqkv, bqkv, wo, bo, k_cache, v_cache,
      *mask_args)
    return y, k2, v2


def _fused_decode_layer_ok(x, wqkv, k_cache, v_cache, n_heads) -> bool:
    """Geometry/flag gate for the fused per-layer decode kernel.
    PTPU_FUSED_DECODE=1 enables (default off until the on-chip A/B
    promotes it); =0 hard-off."""
    import os

    if os.environ.get("PTPU_FUSED_DECODE") != "1":
        return False
    if not (_on_tpu() or _interpret()):
        _count_path("fused_decode_fallback:off_tpu")
        return False
    b, hd = x.shape[0], x.shape[-1]
    d = hd // n_heads
    if d not in (64, 128, 256) or hd % 128 != 0:
        _count_path("fused_decode_fallback:head_geometry")
        return False
    if k_cache.ndim != 3 or k_cache.shape[1] % 128 != 0:
        _count_path("fused_decode_fallback:cache_shape")
        return False
    if not (x.dtype == wqkv.dtype == k_cache.dtype == v_cache.dtype):
        _count_path("fused_decode_fallback:dtype_mix")
        return False
    if x.dtype not in (jnp.bfloat16, jnp.float32):
        # the kernel's compute-dtype pick only handles bf16/f32; a uniform
        # f16 model would hand _dot_f32 mixed f32xf16 operands
        _count_path("fused_decode_fallback:dtype_unsupported")
        return False
    # resident weights must leave room for double-buffered cache slabs
    wbytes = (hd * 3 * hd + hd * hd) * jnp.dtype(wqkv.dtype).itemsize
    if wbytes > 8 * 2**20:
        _count_path("fused_decode_fallback:weights_vmem")
        return False
    _count_path("fused_decode_kernel")
    return True


# ---------------------------------------------------------------------------
# Fused layernorm (SURVEY §7 phase 7; reference fused op family:
# paddle/fluid/operators/fused/fused_bias_dropout_residual_layer_norm —
# single-pass row statistics + affine, fp32 accumulation, one kernel
# instead of the mean/var/normalize/scale chain)
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mu_ref, rs_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                    # [bm, H]
    mu = jnp.mean(x, axis=-1)
    xc = x - mu[:, None]
    var = jnp.mean(xc * xc, axis=-1)
    rs = jax.lax.rsqrt(var + eps)
    y = xc * rs[:, None] * w_ref[...].astype(jnp.float32)[None, :] \
        + b_ref[...].astype(jnp.float32)[None, :]
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu[:, None]
    rs_ref[...] = rs[:, None]


def _ln_bwd_kernel(x_ref, w_ref, mu_ref, rs_ref, dy_ref, dx_ref, dwp_ref,
                   dbp_ref):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)[None, :]
    mu = mu_ref[...]                                      # [bm, 1]
    rs = rs_ref[...]
    dy = dy_ref[...].astype(jnp.float32)
    xhat = (x - mu) * rs
    g = dy * w
    h = x.shape[-1]
    m1 = jnp.sum(g, axis=-1, keepdims=True) / h
    m2 = jnp.sum(g * xhat, axis=-1, keepdims=True) / h
    dx = rs * (g - m1 - xhat * m2)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dwp_ref[...] = jnp.sum(dy * xhat, axis=0)[None, :]
    dbp_ref[...] = jnp.sum(dy, axis=0)[None, :]


def _ln_block_rows(n):
    for bm in (256, 128, 8):
        if n % bm == 0:
            return bm
    return None


def ln_geometry_ok(n, h):
    """Gate for the fused layernorm kernel: whole lane tiles in H,
    divisible row blocks, a live TPU (or interpret mode)."""
    if not (_on_tpu() or _interpret()):
        _count_path("ln_fallback:off_tpu")
        return False
    if h % 128 != 0 or _ln_block_rows(n) is None:
        _count_path("ln_fallback:geometry")
        return False
    _count_path("ln_kernel")
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layernorm_2d(x2, w, b, eps):
    y, _, _ = _ln_fwd(x2, w, b, eps)
    return y


def _ln_fwd(x2, w, b, eps):
    from jax.experimental import pallas as pl

    n, h = x2.shape
    bm = _ln_block_rows(n)
    # match the XLA path's promotion: bf16 x with fp32 norm params (the
    # keep-norm-params-fp32 recipe) produces fp32 output on both paths
    out_dt = jnp.promote_types(jnp.promote_types(x2.dtype, w.dtype), b.dtype)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(n // bm,),
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), out_dt),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, w, b)


def _ln_vjp_fwd(x2, w, b, eps):
    y, mu, rs = _ln_fwd(x2, w, b, eps)
    return y, (x2, w, b, mu, rs)


def _ln_vjp_bwd(eps, res, dy):
    from jax.experimental import pallas as pl

    x2, w, b, mu, rs = res
    n, h = x2.shape
    bm = _ln_block_rows(n)
    grid = n // bm
    dx, dwp, dbp = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((grid, h), jnp.float32),
            jax.ShapeDtypeStruct((grid, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, w, mu, rs, dy)
    dw = jnp.sum(dwp, axis=0).astype(w.dtype)
    db = jnp.sum(dbp, axis=0).astype(b.dtype)
    return dx, dw, db


fused_layernorm_2d.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layernorm_arrays(x, w, b, eps=1e-5):
    """LayerNorm over the LAST axis with the Pallas kernel. Callers gate
    on ln_geometry_ok first (PTPU_ATTN_DEBUG counts the decisions)."""
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    y = fused_layernorm_2d(x2, w, b, float(eps))
    return y.reshape(x.shape)


# ---------------------------------------------------------------------------
# Fused FFN (SURVEY §7 phase 7; reference: fused_feedforward_op.cu) —
# y = act(x @ W1 + b1) @ W2 (+ caller's bias): row-blocked with the
# intermediate accumulated per block, so the [tokens, I] activation never
# round-trips HBM in the forward. Backward recomputes it in XLA (the
# remat trade the kernel exists to make).
# ---------------------------------------------------------------------------

def _ffn_act(u, act):
    if act == "gelu":
        # erf-exact: matches F.gelu's default (approximate=False)
        return jax.nn.gelu(u, approximate=False)
    if act == "gelu_tanh":
        return jax.nn.gelu(u, approximate=True)
    if act == "relu":
        return jnp.maximum(u, 0.0)
    raise ValueError(f"fused_ffn: unsupported activation {act!r}")


def _ffn_fwd_kernel(x_ref, w1_ref, b1_ref, w2_ref, y_ref, *, block_i, act):
    x = x_ref[...]                                    # [bm, H]
    n_ib = w1_ref.shape[1] // block_i
    acc = jnp.zeros((x.shape[0], w2_ref.shape[1]), jnp.float32)

    def body(ib, acc):
        from jax.experimental import pallas as pl

        w1 = w1_ref[:, pl.dslice(ib * block_i, block_i)]     # [H, bi]
        b1 = b1_ref[pl.dslice(ib * block_i, block_i)]        # [bi]
        w2 = w2_ref[pl.dslice(ib * block_i, block_i), :]     # [bi, H2]
        u = _dot_f32(x, w1) + b1[None, :].astype(jnp.float32)
        h = _ffn_act(u, act).astype(x.dtype)
        return acc + _dot_f32(h, w2)

    acc = jax.lax.fori_loop(0, n_ib, body, acc)
    y_ref[...] = acc.astype(y_ref.dtype)


def ffn_geometry_ok(n_rows, h, i, h2):
    if not (_on_tpu() or _interpret()):
        _count_path("ffn_fallback:off_tpu")
        return False
    if (h % 128 or i % 128 or h2 % 128
            or _ln_block_rows(n_rows) is None):
        _count_path("ffn_fallback:geometry")
        return False
    _count_path("ffn_kernel")
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_ffn_2d(x2, w1, b1, w2, act):
    from jax.experimental import pallas as pl

    n, h = x2.shape
    i = w1.shape[1]
    h2 = w2.shape[1]
    bm = _ln_block_rows(n)
    block_i = 512 if i % 512 == 0 else 128
    return pl.pallas_call(
        functools.partial(_ffn_fwd_kernel, block_i=block_i, act=act),
        grid=(n // bm,),
        in_specs=[
            pl.BlockSpec((bm, h), lambda r: (r, 0)),
            pl.BlockSpec((h, i), lambda r: (0, 0)),
            pl.BlockSpec((i,), lambda r: (0,)),
            pl.BlockSpec((i, h2), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, h2), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h2), x2.dtype),
        interpret=_interpret(),
    )(x2, w1, b1, w2)


def _ffn_vjp_fwd(x2, w1, b1, w2, act):
    return fused_ffn_2d(x2, w1, b1, w2, act), (x2, w1, b1, w2)


def _ffn_vjp_bwd(act, res, dy):
    # recompute-based backward in plain XLA: materializes [n, I] here
    # (standard remat trade; the fwd saved that HBM round-trip)
    x2, w1, b1, w2 = res

    def ref(x2, w1, b1, w2):
        u = (x2.astype(jnp.float32) @ w1.astype(jnp.float32)
             + b1.astype(jnp.float32)[None, :])
        h = _ffn_act(u, act).astype(x2.dtype)
        return (h @ w2).astype(x2.dtype)

    _, vjp = jax.vjp(ref, x2, w1, b1, w2)
    return vjp(dy)


fused_ffn_2d.defvjp(_ffn_vjp_fwd, _ffn_vjp_bwd)


def fused_ffn_arrays(x, w1, b1, w2, act="gelu"):
    """Row-blocked fused FFN over the last axis. Callers gate on
    ffn_geometry_ok first. Returns act(x @ w1 + b1) @ w2 (caller adds
    the second bias / dropout / residual)."""
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    y = fused_ffn_2d(x2, w1, b1, w2, act)
    return y.reshape(x.shape[:-1] + (w2.shape[1],))


def maybe_fused_ffn(x, w1, b1, w2, act):
    """Shared gate + dispatch for Tensor-level callers (GPTMLP,
    incubate.FusedFeedForward): returns act(x@w1+b1)@w2 through the
    kernel when the flag/bias/dtype/geometry contract holds, else None —
    the caller then runs its own XLA formulation. Dispatches under
    'linear' so AMP treats both paths identically."""
    if _os.environ.get("PTPU_PALLAS_FFN") != "1":
        return None
    if b1 is None:
        return None
    if not (x.dtype == w1.dtype == w2.dtype):
        _count_path("ffn_fallback:dtype_mix")
        return None
    n_rows = 1
    for d in x.shape[:-1]:
        n_rows *= int(d)
    if not ffn_geometry_ok(n_rows, int(x.shape[-1]), int(w1.shape[-1]),
                           int(w2.shape[-1])):
        return None
    return apply(
        lambda a, wa, ba, wb: fused_ffn_arrays(a, wa, ba, wb, act=act),
        x, w1, b1, w2, name="linear")
