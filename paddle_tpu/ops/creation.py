"""Tensor creation ops (reference: python/paddle/tensor/creation.py,
random.py — lowered here directly to jnp/jax.random instead of phi kernels).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..core.dtype import convert_dtype
from ..core import random as _random

__all__ = [
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "eye",
    "diag",
    "tril",
    "triu",
    "rand",
    "randn",
    "randint",
    "uniform",
    "normal",
    "randperm",
    "bernoulli",
    "multinomial",
    "assign",
    "clone",
    "meshgrid",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype="float32"):
    return Tensor(jnp.zeros(_shape(shape), convert_dtype(dtype)))


def ones(shape, dtype="float32"):
    return Tensor(jnp.ones(_shape(shape), convert_dtype(dtype)))


def full(shape, fill_value, dtype="float32"):
    return Tensor(jnp.full(_shape(shape), fill_value, convert_dtype(dtype)))


def empty(shape, dtype="float32"):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None):
    return Tensor(jnp.zeros_like(x._data, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None):
    return Tensor(jnp.ones_like(x._data, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    return Tensor(jnp.full_like(x._data, fill_value, dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python scalars")
    dtype = convert_dtype(dtype)
    if dtype is None:
        py = (start, end, step)
        dtype = (
            convert_dtype("float32")
            if any(isinstance(v, float) for v in py)
            else convert_dtype("int64")
        )
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype="float32"):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32"):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype)))


def diag(x, offset=0):
    from ..core.dispatch import apply

    return apply(lambda a: jnp.diag(a, k=offset), x, name="diag")


def tril(x, diagonal=0):
    from ..core.dispatch import apply

    return apply(lambda a: jnp.tril(a, diagonal), x, name="tril")


def triu(x, diagonal=0):
    from ..core.dispatch import apply

    return apply(lambda a: jnp.triu(a, diagonal), x, name="triu")


# -- random -----------------------------------------------------------------


def rand(shape, dtype="float32"):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype="float32"):
    dtype = convert_dtype(dtype)
    return Tensor(jax.random.normal(_random.next_key(), _shape(shape), dtype))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(
            _random.next_key(), _shape(shape), low, high, convert_dtype(dtype)
        )
    )


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    dtype = convert_dtype(dtype)
    key = jax.random.PRNGKey(seed) if seed else _random.next_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), dtype, minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=(1,)):
    base = jax.random.normal(_random.next_key(), _shape(shape), jnp.float32)
    return Tensor(base * std + mean)


def randperm(n, dtype="int64"):
    return Tensor(
        jax.random.permutation(_random.next_key(), n).astype(convert_dtype(dtype))
    )


def bernoulli(x):
    p = x._data
    return Tensor(
        jax.random.bernoulli(_random.next_key(), p, p.shape).astype(p.dtype)
    )


def multinomial(x, num_samples=1, replacement=False):
    probs = x._data
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(
            _random.next_key(), logits, axis=-1, shape=(*logits.shape[:-1], num_samples)
        )
    else:
        # Gumbel top-k trick for sampling without replacement.
        g = jax.random.gumbel(_random.next_key(), logits.shape, logits.dtype)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def assign(x, output=None):
    from ..core.dispatch import apply

    if not isinstance(x, Tensor):
        x = to_tensor(x)
    out = apply(lambda a: a + 0, x, name="assign")
    if output is not None:
        output._data = out._data
        output._grad_node = out._grad_node
        output._out_index = out._out_index
        output.stop_gradient = out.stop_gradient
        return output
    return out


def clone(x):
    return assign(x)


def meshgrid(*args):
    arrays = [a._data for a in args]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor(o) for o in outs]
