"""Math ops (reference: python/paddle/tensor/math.py + phi kernels
paddle/phi/kernels/{cpu,gpu}/*_kernel.cc — here each op is one pure jnp
function; XLA provides the fused CPU/TPU kernels)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..core.dtype import convert_dtype

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "pow", "matmul", "bmm", "dot", "mm", "inner", "outer",
    "sum", "mean", "max", "min", "prod", "amax", "amin",
    "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "reciprocal", "sign",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "floor", "ceil", "round", "trunc", "frac",
    "maximum", "minimum", "fmax", "fmin",
    "clip", "cumsum", "cumprod", "logsumexp", "logcumsumexp",
    "isnan", "isinf", "isfinite", "nan_to_num",
    "erf", "erfinv", "lgamma", "digamma",
    "conj", "real", "imag", "angle",
    "stanh", "rad2deg", "deg2rad",
    "addmm", "einsum", "kron", "trace", "diagonal",
    "mod", "lerp", "hypot", "gcd", "lcm",
]


def _t(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype=jnp.float32 if isinstance(x, float) else None))


def _binary(fn, name):
    def op(x, y, name_=None):
        return apply(fn, _t(x), _t(y), name=name)

    op.__name__ = name
    return op


def _unary(fn, name):
    def op(x, name_=None):
        return apply(fn, x, name=name)

    op.__name__ = name
    return op


add = _binary(lambda a, b: a + b, "add")
subtract = _binary(lambda a, b: a - b, "subtract")
multiply = _binary(lambda a, b: a * b, "multiply")
divide = _binary(lambda a, b: a / b, "divide")
floor_divide = _binary(lambda a, b: jnp.floor_divide(a, b), "floor_divide")
remainder = _binary(lambda a, b: jnp.remainder(a, b), "remainder")
mod = remainder
pow = _binary(lambda a, b: jnp.power(a, b), "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(jnp.hypot, "hypot")

abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
square = _unary(jnp.square, "square")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
sign = _unary(jnp.sign, "sign")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda a: a - jnp.trunc(a), "frac")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
angle = _unary(jnp.angle, "angle")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
digamma = _unary(jax.scipy.special.digamma, "digamma")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
deg2rad = _unary(jnp.deg2rad, "deg2rad")


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return apply(lambda a: scale_b * jnp.tanh(a * scale_a), x, name="stanh")


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply(fn, x, y, name="matmul")


mm = matmul
bmm = matmul


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y, name="dot")


def inner(x, y):
    return apply(jnp.inner, x, y, name="inner")


def outer(x, y):
    return apply(lambda a, b: jnp.outer(a, b), x, y, name="outer")


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return apply(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y, name="addmm"
    )


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(fn, name, int_promote=False):
    def op(x, axis=None, keepdim=False, name_=None, dtype=None):
        ax = _norm_axis(axis)

        def f(a):
            out = fn(a, axis=ax, keepdims=keepdim)
            if dtype is not None:
                out = out.astype(convert_dtype(dtype))
            elif int_promote and jnp.issubdtype(a.dtype, jnp.integer):
                out = out.astype(jnp.int64)
            return out

        return apply(f, x, name=name)

    op.__name__ = name
    return op


sum = _reduce(jnp.sum, "sum", int_promote=True)
mean = _reduce(jnp.mean, "mean")
prod = _reduce(jnp.prod, "prod", int_promote=True)
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")


def max(x, axis=None, keepdim=False, name=None):
    return amax(x, axis=axis, keepdim=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return amin(x, axis=axis, keepdim=keepdim)


def clip(x, min=None, max=None, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, name="clip")


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            out = jnp.cumsum(a)
        else:
            out = jnp.cumsum(a, axis=axis)
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out

    return apply(f, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    def f(a):
        out = jnp.cumprod(a, axis=dim)
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out

    return apply(f, x, name="cumprod")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(
        lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
        x,
        name="logsumexp",
    )


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)

    return apply(f, x, name="logcumsumexp")


isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x,
        name="nan_to_num",
    )


def einsum(equation, *operands):
    return apply(
        lambda *ops: jnp.einsum(equation, *ops), *operands, name="einsum"
    )


def kron(x, y):
    return apply(jnp.kron, x, y, name="kron")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        x,
        name="trace",
    )


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x,
        name="diagonal",
    )


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), x, y, name="lerp")


def gcd(x, y):
    return apply(jnp.gcd, x, y, name="gcd")


def lcm(x, y):
    return apply(jnp.lcm, x, y, name="lcm")
