"""Inplace-op contract shared by Tensor-method variants (ops.extras),
the functional variants (nn.functional.extras), and __setitem__
(ops/__init__): record the op against a FROZEN pre-mutation snapshot,
then rebind the mutated tensor to the producing node. Split into its own
module so extras can import it during the ops package's own import."""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["_autograd_snapshot", "_inplace_rebind", "make_inplace"]


def _autograd_snapshot(x):
    """Frozen pre-mutation view for recording an inplace op: the node must
    hold a Tensor whose _data/_version never change afterwards (the lazy
    pullback re-reads input _data at backward; the version guard enforces
    it). Mirrors the reference contract: inplace on a grad-requiring LEAF
    is an error (eager_method.cc inplace checks / torch semantics)."""
    from ..autograd import tape

    if (tape.is_grad_enabled() and not x.stop_gradient
            and getattr(x, "_grad_node", None) is None):
        raise RuntimeError(
            "a leaf Tensor that requires grad is being used in an in-place "
            "operation; operate on a computed value or use no_grad()")
    snap = Tensor(x._data, stop_gradient=x.stop_gradient)
    snap._grad_node = getattr(x, "_grad_node", None)
    snap._out_index = getattr(x, "_out_index", 0)
    return snap


def _inplace_rebind(x, out):
    x._data = out._data            # bumps the inplace version
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    if not out.stop_gradient:
        x.stop_gradient = False


def make_inplace(fn, name=None):
    """fn(snapshot, *args, **kwargs) -> Tensor; returns the inplace op."""

    def op(x, *a, **k):
        snap = _autograd_snapshot(x)
        out = fn(snap, *a, **k)
        _inplace_rebind(x, out)
        return x

    op.__name__ = name or getattr(fn, "__name__", "op") + "_"
    return op
