"""TensorArray API (reference: python/paddle/tensor/array.py — the
LoDTensorArray used by dynamic models and control flow).

TPU-native position: in eager mode a TensorArray is a plain Python list of
Tensors (the reference dygraph mode does exactly this — array.py:24 "In
dynamic mode, a list of Tensor"); under jit, code that needs an
append-per-iteration pattern should use lax.scan-shaped ops (stacked
Tensors), which is what the model zoo does. These functions provide the
reference's surface: create_array / array_write / array_read /
array_length, with write-past-end zero-padding semantics.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def _index(i) -> int:
    if isinstance(i, Tensor):
        return int(i.numpy())
    return int(i)


def create_array(dtype="float32", initialized_list=None):
    """New TensorArray (a Python list in the TPU eager design)."""
    out = []
    if initialized_list is not None:
        for t in initialized_list:
            if not isinstance(t, Tensor):
                t = Tensor(jnp.asarray(t))
            out.append(t)
    return out


def array_write(x, i, array=None):
    """Write x at index i; growing writes pad intermediate slots with
    zeros_like(x) (reference fills with empty tensors; zeros keeps reads
    well-defined on TPU where empty tensors have no meaning)."""
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    i = _index(i)
    if i < 0:
        raise ValueError(f"array_write index must be >= 0, got {i}")
    if array is None:
        array = []
    while len(array) < i:
        array.append(Tensor(jnp.zeros_like(x._data)))
    if len(array) == i:
        array.append(x)
    else:
        array[i] = x
    return array

def array_read(array, i):
    i = _index(i)
    if not 0 <= i < len(array):
        raise IndexError(f"array_read index {i} out of range [0, {len(array)})")
    return array[i]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))
