"""Runtime kernel autotuning.

Reference analog: paddle/phi/kernels/autotune/ (cache.h AutoTuneCache keyed
by kernel signature, auto_tune_base.h measured candidate selection, enabled
via FLAGS_use_autotune) and python/paddle/incubate/autotune.py set_config.

TPU-native re-design: the tunable surface is Pallas grid/block geometry
(the analog of the reference's cuDNN algo / transpose-variant choice). A
candidate sweep runs the REAL kernel on zero-filled inputs of the actual
shapes — legal while tracing an outer jit, because dispatching concrete
ops from Python during trace just runs them — and the winner is memoized
by (kernel, static key). The cache can persist to JSON across processes
(the analog of autotune cache serialization) via PTPU_AUTOTUNE_CACHE.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Iterable, Sequence, Tuple

import jax

from ..framework.core_ import get_flag
from .. import monitor
from ..profiler import RecordEvent

__all__ = ["AutoTuneCache", "autotune", "cache", "set_config"]


class AutoTuneCache:
    """Shape-keyed best-config store with hit/miss stats (cache.h analog)."""

    def __init__(self):
        self._store: Dict[str, Dict[str, Any]] = {}
        self.hits = 0
        self.misses = 0
        path = os.environ.get("PTPU_AUTOTUNE_CACHE")
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._store = json.load(f)
            except (OSError, ValueError):
                pass

    @staticmethod
    def _key(kernel: str, key: Tuple) -> str:
        return kernel + "|" + repr(key)

    def get(self, kernel: str, key: Tuple):
        k = self._key(kernel, key)
        if k in self._store:
            self.hits += 1
            monitor.counter("autotune/hits").inc()
            return self._store[k]
        self.misses += 1
        monitor.counter("autotune/misses").inc()
        return None

    def put(self, kernel: str, key: Tuple, config: Any):
        self._store[self._key(kernel, key)] = config

    def clear(self):
        self._store.clear()
        self.hits = self.misses = 0

    def cache_hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def save(self, path: str | None = None):
        path = path or os.environ.get("PTPU_AUTOTUNE_CACHE")
        if path:
            with open(path, "w") as f:
                json.dump(self._store, f)


cache = AutoTuneCache()

# live hit-rate of the process-wide cache, sampled at monitor export time
monitor.gauge("autotune/hit_rate", fn=cache.cache_hit_rate)

_config = {"kernel": {"enable": True, "tuning_range": [1, 10]}}


def set_config(config: dict | str | None = None):
    """paddle.incubate.autotune.set_config parity: accepts a dict or a path
    to a JSON file with {"kernel": {"enable": bool}} (layout/dataloader
    sections are accepted and ignored — XLA owns layouts on TPU)."""
    global _config
    if config is None:
        _config = {"kernel": {"enable": True}}
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for section in ("kernel", "layout", "dataloader"):
        if section in config:
            _config.setdefault(section, {}).update(config[section])


def _enabled() -> bool:
    return bool(get_flag("FLAGS_use_autotune", True)) and _config.get(
        "kernel", {}).get("enable", True)


def _measure(fn: Callable[[], Any], iters: int = 3) -> float:
    jax.block_until_ready(fn())  # compile + warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def autotune(
    kernel: str,
    key: Tuple,
    candidates: Sequence[Any],
    runner: Callable[[Any], Callable[[], Any]] | None = None,
) -> Any:
    """Pick the best of `candidates` for `kernel` at static `key`.

    runner(cfg) -> zero-arg callable running the real kernel with cfg on
    representative inputs. When tuning is disabled, the runner fails, or
    only one candidate exists, the first candidate (the heuristic default)
    wins. Results are memoized in the process-wide cache.
    """
    candidates = list(candidates)
    if not candidates:
        raise ValueError("autotune needs at least one candidate")
    got = cache.get(kernel, key)
    if got is not None:
        return got
    choice = candidates[0]
    if len(candidates) > 1 and runner is not None and _enabled():
        best_t = float("inf")
        with RecordEvent("autotune/sweep"), \
                monitor.timer("autotune/sweep_time", kernel=kernel):
            for cand in candidates:
                try:
                    t = _measure(runner(cand))
                except Exception:
                    continue
                if t < best_t:
                    best_t, choice = t, cand
    cache.put(kernel, key, choice)
    return choice
