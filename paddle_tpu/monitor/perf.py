"""Performance attribution — MFU/roofline accounting on XLA's own
compile-time analyses (the *how close to the hardware* half of the
monitor subsystem; PR-1 metrics say how much, PR-5 traces say where,
this module says how far from optimal).

Three data sources, one registry:

- **compiled-program accounting** — for every compiled step program the
  jit layer (``jit.CompiledFunction``) and the serving engine hand this
  module XLA's ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/generated-code bytes).
  Combined with the chip's peak numbers (``chip_spec()``) and measured,
  **synced** wall time per call, each program gets: achieved FLOP/s,
  MFU vs the bf16 peak, arithmetic intensity vs the roofline ridge
  (compute- vs memory-bound), the roofline-optimal step time, and the
  achieved-vs-optimal ratio — the number a perf PR must move.
- **step-segment breakdown** — named, properly-synced sub-step timers:
  the serving decode step reports prep/model/sampler in situ, and
  ``LLMEngine.decode_breakdown()`` attributes the inside of the fused
  program (block gather, attention, cache update, sampler) against each
  segment's own cost-analysis prediction; ``hapi.Model`` splits the
  eager train step into forward/backward/optimizer.
- **HBM attribution** — per-program peak-bytes estimate and headroom vs
  the chip's HBM (``perf/hbm_headroom``), the memfit gate's live twin.

Gate: ``PTPU_PERF=1`` (default OFF — perf mode syncs after every timed
call and routes fresh compiles through the AOT path to capture their
analyses, both of which perturb steady-state pipelining; it is a
diagnostic mode, not an always-on tax).  With the gate off every hook
is one module-global read (guarded by the trace_overhead bench gate and
tests/test_perf.py's <1µs check).

Import constraints (shared with trace/flight/serve): importing this
module never imports jax — analyses arrive as plain dicts/objects from
callers that already hold jax, and the jax bits (``measure()``, chip
detection, ``block_until_ready``) import lazily inside functions.

Exported metrics (all literal, lint_metrics-clean):
``perf/mfu`` (overall, callback), ``perf/mfu{fn}``, ``perf/flops{fn}``,
``perf/bytes{fn}``, ``perf/hbm_peak_bytes{fn}``,
``perf/hbm_headroom{fn}``, ``perf/analysis_unavailable{fn}``,
``perf/step_time{fn}`` (histogram), ``perf/segment_time{step,segment}``
(histogram), ``perf/capture_errors{site}``, ``perf/cost_keys_dropped``.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

__all__ = [
    "enabled", "enable", "refresh", "chip_spec", "ChipSpec",
    "normalize_cost_analysis", "capture", "observe", "observe_segment",
    "segment", "measure", "records", "get", "report", "hlo_report",
    "reset", "UNAVAILABLE",
]

UNAVAILABLE = "unavailable"


def _env_enabled() -> bool:
    return os.environ.get("PTPU_PERF", "0").strip().lower() not in (
        "0", "false", "off", "")


_enabled = _env_enabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip perf accounting on/off at runtime (overrides PTPU_PERF)."""
    global _enabled
    _enabled = bool(on)


def refresh():
    """Re-read PTPU_PERF from the environment."""
    global _enabled
    _enabled = _env_enabled()


def _registry():
    from . import get_registry

    return get_registry()


# -- chip model -------------------------------------------------------------

class ChipSpec:
    """Peak numbers the roofline is drawn against.  ``peak_flops`` is the
    dense bf16 (MXU) peak in FLOP/s, ``hbm_bw`` bytes/s, ``hbm_bytes``
    per-device HBM capacity.  Env overrides (for A/B or odd hosts):
    PTPU_PERF_PEAK_FLOPS, PTPU_PERF_HBM_GBS (GB/s), PTPU_PERF_HBM_GIB."""

    __slots__ = ("name", "peak_flops", "hbm_bw", "hbm_bytes")

    def __init__(self, name, peak_flops, hbm_bw, hbm_bytes):
        self.name = name
        self.peak_flops = float(peak_flops)
        self.hbm_bw = float(hbm_bw)
        self.hbm_bytes = float(hbm_bytes)

    @property
    def ridge(self) -> float:
        """Roofline ridge point (FLOP/byte): programs above it are
        compute-bound, below it memory-bound."""
        return self.peak_flops / max(self.hbm_bw, 1.0)

    def __repr__(self):
        return (f"ChipSpec({self.name}, {self.peak_flops/1e12:.0f} TFLOP/s,"
                f" {self.hbm_bw/1e9:.0f} GB/s, "
                f"{self.hbm_bytes/2**30:.0f} GiB)")


# (peak bf16 FLOP/s, HBM bytes/s, HBM bytes) — v5e numbers match bench.py's
# PEAK_BF16/hbm_bw constants so MFU here and vs_baseline there agree.
_KNOWN_CHIPS = (
    ("v5 lite", ("tpu-v5e", 197e12, 819e9, 16 * 2**30)),
    ("v5e", ("tpu-v5e", 197e12, 819e9, 16 * 2**30)),
    ("v5p", ("tpu-v5p", 459e12, 2765e9, 95 * 2**30)),
    ("v4", ("tpu-v4", 275e12, 1228e9, 32 * 2**30)),
    ("v3", ("tpu-v3", 123e12, 900e9, 16 * 2**30)),
)


def _host_ram_bytes() -> float:
    try:
        return float(os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):
        return 16 * 2**30


_chip = None
_chip_lock = threading.Lock()


def chip_spec(refresh_probe: bool = False) -> ChipSpec:
    """The current backend's ChipSpec (probed once, cached).  CPU hosts
    get the same stand-in peaks bench.py's cpu-smoke baselines use, and
    HBM capacity falls back to host RAM — the numbers still rank
    segments correctly relative to each other, which is what the
    attribution table is for."""
    global _chip
    if _chip is not None and not refresh_probe:
        return _chip
    with _chip_lock:
        if _chip is not None and not refresh_probe:
            return _chip
        name, peak, bw, cap = "cpu", 5e9, 50e9, _host_ram_bytes()
        try:
            import jax

            dev = jax.devices()[0]
            kind = f"{getattr(dev, 'device_kind', '')} {dev.platform}".lower()
            if "tpu" in kind or "axon" in kind:
                name, peak, bw, cap = "tpu", 197e12, 819e9, 16 * 2**30
                for marker, spec in _KNOWN_CHIPS:
                    if marker in kind:
                        name, peak, bw, cap = spec
                        break
        except Exception:   # ptpu-check[silent-except]: a wedged/absent backend must not
            # take down perf accounting — the cpu stand-in still ranks
            _registry().counter(
                "perf/capture_errors",
                "failed analysis/probe captures").labels(
                site="chip_probe").inc()
        peak = float(os.environ.get("PTPU_PERF_PEAK_FLOPS", peak))
        bw = float(os.environ.get("PTPU_PERF_HBM_GBS", bw / 1e9)) * 1e9
        cap = float(os.environ.get("PTPU_PERF_HBM_GIB", cap / 2**30)) * 2**30
        _chip = ChipSpec(name, peak, bw, cap)
        return _chip


# -- analysis normalization -------------------------------------------------

def normalize_cost_analysis(analysis):
    """XLA's ``cost_analysis()`` across jax versions returns a dict, a
    one-element list of dicts, or None; entries may be non-scalar
    (utilization maps).  Returns ``(cost, dropped)``: scalar-only dict
    plus the count of non-scalar entries it had to drop — counted, never
    silent (the CostModel bug this module dedupes away)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return {}, 0
    cost, dropped = {}, 0
    for k, v in analysis.items():
        if isinstance(v, bool):
            dropped += 1
        elif isinstance(v, (int, float)):
            cost[str(k)] = float(v)
        else:
            dropped += 1
    return cost, dropped


def _memory_dict(mem) -> dict:
    """CompiledMemoryStats → plain dict + derived peak estimate (the
    memfit gate's formula: arguments + temps − aliased)."""
    if isinstance(mem, dict):
        out = {k: int(v) for k, v in mem.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    else:
        out = {}
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
            v = getattr(mem, k, None)
            if isinstance(v, (int, float)):
                out[k] = int(v)
    if out and "peak_bytes_estimate" not in out:
        out["peak_bytes_estimate"] = (
            out.get("argument_size_in_bytes", 0)
            + out.get("temp_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0))
    return out


# -- the per-program record -------------------------------------------------

class FnPerf:
    """One compiled program's (or named segment's) accounting: what XLA
    says it must do (cost/memory) and what the host measured it doing
    (synced wall times)."""

    __slots__ = ("label", "cost", "memory", "dropped_keys",
                 "calls", "total_s", "min_s", "last_s")

    def __init__(self, label):
        self.label = label
        self.cost = {}
        self.memory = {}
        self.dropped_keys = 0
        self.calls = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.last_s = 0.0

    # -- what XLA promised -------------------------------------------------
    @property
    def flops(self):
        return self.cost.get("flops")

    @property
    def bytes_accessed(self):
        return self.cost.get("bytes accessed")

    @property
    def available(self) -> bool:
        """True when the analysis yielded usable flops OR bytes —
        zero-flop programs (pure copy/scatter, e.g. a paged cache
        update) are legitimately memory-roofline-only and must still
        rank.  CPU/stat-less backends can return empty dicts — those
        records stay visible but every derived figure reads
        'unavailable' instead of garbage."""
        f, b = self.flops, self.bytes_accessed
        return bool((f and f > 0) or (b and b > 0))

    @property
    def peak_bytes(self):
        return self.memory.get("peak_bytes_estimate")

    @property
    def intensity(self):
        """Arithmetic intensity, FLOP per HBM byte (0.0 for a zero-flop
        copy program — maximally memory-bound, not unavailable)."""
        if self.flops is None or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def bound(self, chip=None) -> str:
        ai = self.intensity
        if ai is None:
            return UNAVAILABLE
        chip = chip or chip_spec()
        return "compute" if ai >= chip.ridge else "memory"

    def optimal_s(self, chip=None):
        """Roofline-optimal wall time: the max of pure-compute and
        pure-bandwidth lower bounds (a zero-flop program's bound is
        purely bandwidth)."""
        if not self.available:
            return None
        chip = chip or chip_spec()
        t = (self.flops or 0.0) / chip.peak_flops
        if self.bytes_accessed:
            t = max(t, self.bytes_accessed / chip.hbm_bw)
        return t or None

    # -- what the host measured --------------------------------------------
    def add_wall(self, wall_s: float):
        self.calls += 1
        self.total_s += wall_s
        self.min_s = min(self.min_s, wall_s)
        self.last_s = wall_s

    @property
    def best_s(self):
        return self.min_s if self.calls else None

    def mfu(self, chip=None):
        """Achieved fraction of the chip's bf16 peak at the BEST observed
        wall time (min-of-N: host noise only ever slows a step down).
        None for zero-flop programs — their roofline figure is
        achieved_vs_optimal, not MFU."""
        if not self.flops or not self.calls or self.min_s <= 0:
            return None
        chip = chip or chip_spec()
        return self.flops / self.min_s / chip.peak_flops

    def achieved_vs_optimal(self, chip=None):
        """optimal/achieved in (0, 1]; 1.0 = running at the roofline.
        The ranking key of the attribution table — the segment with the
        SMALLEST ratio is the next optimization target.  Clamped at 1.0:
        a stand-in chip spec (CPU hosts) can under-state the real peaks,
        and a raw ratio above 1 would just mean "spec too low", not
        "faster than the roofline"."""
        opt = self.optimal_s(chip)
        if opt is None or not self.calls or self.min_s <= 0:
            return None
        return min(1.0, opt / self.min_s)

    def hbm_headroom(self, chip=None):
        pk = self.peak_bytes
        if not pk or pk <= 0:
            return None
        chip = chip or chip_spec()
        return chip.hbm_bytes / pk

    def as_dict(self) -> dict:
        chip = chip_spec()
        return {
            "label": self.label,
            "available": self.available,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "intensity": self.intensity,
            "bound": self.bound(chip),
            "calls": self.calls,
            "wall_best_s": self.best_s,
            "wall_avg_s": (self.total_s / self.calls) if self.calls
            else None,
            "mfu": self.mfu(chip),
            "optimal_s": self.optimal_s(chip),
            "achieved_vs_optimal": self.achieved_vs_optimal(chip),
            "peak_bytes": self.peak_bytes,
            "hbm_headroom": self.hbm_headroom(chip),
            "memory": dict(self.memory),
            "dropped_cost_keys": self.dropped_keys,
        }


_records: "OrderedDict[str, FnPerf]" = OrderedDict()
_rec_lock = threading.Lock()
# dispatched-flops / synced-wall totals behind the overall perf/mfu gauge
_totals = {"flops": 0.0, "wall_s": 0.0}
_mfu_gauge_registered = False


def _overall_mfu() -> float:
    w = _totals["wall_s"]
    if w <= 0:
        return 0.0
    return _totals["flops"] / w / chip_spec().peak_flops


def _ensure_overall_gauge():
    global _mfu_gauge_registered
    if not _mfu_gauge_registered:
        _mfu_gauge_registered = True
        _registry().gauge(
            "perf/mfu",
            "achieved fraction of chip bf16 peak, all analyzed programs",
            fn=_overall_mfu)


def _get_record(label: str) -> FnPerf:
    with _rec_lock:
        rec = _records.get(label)
        if rec is None:
            rec = _records[label] = FnPerf(label)
        return rec


def _match_record(label: str, cost: dict) -> FnPerf:
    """The record for `label` whose analysis matches `cost` — two DIFFERENT
    programs sharing a label (a recompiled step at a new batch shape, two
    '<lambda>'s through CostModel) must not merge, or wall times measured
    on one program get ratioed against the other's flops and the MFU /
    ach-opt ranking is fiction.  The first distinct program keeps the bare
    label; later ones get `label#2`, `label#3`, ...  An empty `cost`
    (stat-less backend) reuses the base record, as does a matching one."""
    with _rec_lock:
        base, i = label, 1
        while True:
            rec = _records.get(label)
            if rec is None:
                rec = _records[label] = FnPerf(label)
                return rec
            if not cost or not rec.cost or rec.cost == cost:
                return rec
            i += 1
            label = f"{base}#{i}"


def records() -> list:
    """Every FnPerf record, insertion-ordered."""
    with _rec_lock:
        return list(_records.values())


def get(label: str):
    with _rec_lock:
        return _records.get(label)


def reset():
    """Drop every record (incl. captured HLO analyses) and zero the MFU
    totals (tests)."""
    with _rec_lock:
        _records.clear()
        _totals["flops"] = 0.0
        _totals["wall_s"] = 0.0
    from . import hlo as _hlo

    _hlo.reset()


# -- capture / observe ------------------------------------------------------

def capture(label, lowered=None, compiled=None, cost=None, memory=None):
    """Attach XLA's analyses to `label`'s record and export the static
    gauges.  Accepts the jax AOT objects (``lowered``/``compiled``) or
    pre-extracted dicts; every probe failure is counted, never raised —
    a backend without analysis support leaves the record marked
    unavailable, and derived gauges (mfu/headroom) are simply not set
    (the graceful-degradation contract of tests/test_perf.py).

    Returns the record the analyses landed in — a DIFFERENT program
    under the same label (see ``_match_record``) gets a ``label#N``
    record, so callers must route subsequent ``observe()`` calls via
    ``rec.label``, not the label they passed in."""
    m = _registry()
    if cost is None:
        for site, obj in (("compiled", compiled), ("lowered", lowered)):
            if obj is None:
                continue
            try:
                cost = obj.cost_analysis()
                break
            except Exception:   # ptpu-check[silent-except]: analysis support varies by
                # backend/jax version; counted, record stays unavailable
                m.counter("perf/capture_errors",
                          "failed analysis/probe captures").labels(
                    site=f"cost_{site}").inc()
    if memory is None and compiled is not None:
        try:
            memory = compiled.memory_analysis()
        except Exception:   # ptpu-check[silent-except]: same contract as cost above
            m.counter("perf/capture_errors",
                      "failed analysis/probe captures").labels(
                site="memory").inc()
    norm, dropped = normalize_cost_analysis(cost)
    rec = _match_record(label, norm)
    label = rec.label
    if norm:
        rec.cost = norm
    rec.dropped_keys += dropped
    if dropped:
        m.counter("perf/cost_keys_dropped",
                  "non-scalar cost_analysis entries skipped").inc(dropped)
    if memory is not None:
        md = _memory_dict(memory)
        if md:
            rec.memory = md
    chip = chip_spec()
    if rec.available:
        if rec.flops is not None:
            m.gauge("perf/flops",
                    "XLA cost-analysis FLOPs per call").labels(
                fn=label).set(rec.flops)
        if rec.bytes_accessed:
            m.gauge("perf/bytes",
                    "XLA cost-analysis HBM bytes per call").labels(
                fn=label).set(rec.bytes_accessed)
        # a prior failed capture may have flagged this fn unavailable;
        # the marker must not outlive the condition it reports
        m.gauge("perf/analysis_unavailable",
                "1 = backend returned no usable cost analysis").labels(
            fn=label).set(0)
    else:
        m.gauge("perf/analysis_unavailable",
                "1 = backend returned no usable cost analysis").labels(
            fn=label).set(1)
    pk = rec.peak_bytes
    if pk and pk > 0:
        m.gauge("perf/hbm_peak_bytes",
                "compile-time peak live bytes estimate").labels(
            fn=label).set(pk)
        m.gauge("perf/hbm_headroom",
                "chip HBM / compile-time peak bytes").labels(
            fn=label).set(chip.hbm_bytes / pk)
    # ISSUE 12: HLO-level kernel attribution off the SAME executable this
    # signature's one AOT compile already produced — text only, parsed by
    # the stdlib hlo module; any failure degrades to an unavailable
    # record (counted), never a broken capture
    if _enabled and compiled is not None:
        from . import hlo as _hlo

        text = None
        try:
            text = compiled.as_text()
        except Exception:   # ptpu-check[silent-except]: as_text support varies by
            # backend/jax version; the program-level analyses above stand
            m.counter("perf/capture_errors",
                      "failed analysis/probe captures").labels(
                site="hlo_text").inc()
        if text is not None:
            _hlo.capture(label, text)
    _ensure_overall_gauge()
    return rec


def observe(label: str, wall_s: float):
    """Record one synced call of `label` taking ``wall_s`` seconds and
    refresh its derived gauges."""
    m = _registry()
    rec = _get_record(label)
    rec.add_wall(wall_s)
    m.histogram("perf/step_time",
                "synced wall seconds per analyzed program").labels(
        fn=label).observe(wall_s)
    if rec.available:
        with _rec_lock:   # += is a read-modify-write: two perf-on
            # threads would otherwise lose increments and drift the
            # overall perf/mfu callback gauge
            _totals["flops"] += rec.flops or 0.0
            _totals["wall_s"] += wall_s
        mfu = rec.mfu()
        if mfu is not None:
            m.gauge("perf/mfu",
                    "achieved fraction of chip bf16 peak, all analyzed "
                    "programs").labels(fn=label).set(mfu)
    _ensure_overall_gauge()
    return rec


def observe_segment(step: str, name: str, wall_s: float):
    """A named sub-step segment's synced wall time (prep/model/sampler in
    the serving decode step; forward/backward/optimizer in the eager
    train step).  Also lands in the ``step:name`` record so segments and
    whole programs share one attribution table."""
    _registry().histogram(
        "perf/segment_time",
        "synced sub-step segment seconds").labels(
        step=step, segment=name).observe(wall_s)
    return observe(f"{step}:{name}", wall_s)


class _NoopSegment:
    """The shared disabled-mode segment: no allocation, no state — the
    <1µs disabled-overhead guard is met by not constructing anything."""

    __slots__ = ()

    def sync(self, *objs):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SEGMENT = _NoopSegment()


class segment:
    """Properly-synced segment timer::

        with perf.segment("train", "forward") as s:
            loss = model(x)
            s.sync(loss)            # block on these arrays at exit

    No-op (one global read + a shared singleton) when perf is disabled.
    ``sync()`` collects arrays/Tensors/pytrees; exit blocks until they
    are device-complete, so the recorded time is the segment's real wall
    time, not its dispatch time."""

    __slots__ = ("_step", "_name", "_t0", "_targets", "_on")

    def __new__(cls, step: str, name: str):
        if not _enabled:
            return _NOOP_SEGMENT
        return object.__new__(cls)

    def __init__(self, step: str, name: str):
        self._on = True
        self._step = step
        self._name = name
        self._targets = []
        self._t0 = None

    def sync(self, *objs):
        if self._on:
            self._targets.extend(objs)
        return self

    def __enter__(self):
        if self._on:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is None:
            return False
        if self._targets:
            _block_until_ready(self._targets)
        observe_segment(self._step, self._name,
                        time.perf_counter() - self._t0)
        return False


def _block_until_ready(obj):
    import jax

    def leaf(x):
        data = getattr(x, "_data", x)    # Tensor → array
        if hasattr(data, "block_until_ready"):
            data.block_until_ready()

    jax.tree_util.tree_map(leaf, obj)


# -- one-shot measurement (CostModel / breakdown backend) -------------------

def measure(fn, *arrays, label=None, reps: int = 2, donate_argnums=(),
            static_argnums=(), rearm=None):
    """Lower+compile ``fn`` on ``arrays`` (jax AOT path), capture its
    cost/memory analyses, execute it ``reps``+1 times (first run is
    warmup/page-in) with a full sync, and return the record's
    ``as_dict()`` plus ``wall_time_s`` (best synced run).  The shared
    backend of ``CostModel.profile_measure`` and
    ``LLMEngine.decode_breakdown`` — ONE lower/compile/analyze
    convention instead of three hand-rolled ones.

    ``fn`` may already be a ``jax.jit`` object (it is lowered as-is,
    preserving its own donation).  With donation, buffers are re-armed
    between reps: ``rearm(args, out) -> new args`` when given, else the
    single donated position is replaced by the output wholesale (the
    donated-pool ping-pong), else outputs fill donated positions in
    order."""
    import jax

    label = label or getattr(fn, "__name__", "<fn>")
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, donate_argnums=donate_argnums, static_argnums=static_argnums)
    lowered = jitted.lower(*arrays)
    compiled = lowered.compile()
    rec = capture(label, lowered=lowered, compiled=compiled)
    args = tuple(arrays)
    donated = bool(donate_argnums) or rearm is not None
    best = float("inf")
    for _ in range(max(1, int(reps)) + 1):
        t0 = time.perf_counter()
        out = compiled(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
        if rearm is not None:
            args = tuple(rearm(args, out))
        elif donated:
            args = list(args)
            if len(donate_argnums) == 1:
                args[donate_argnums[0]] = out
            else:
                outs = out if isinstance(out, (list, tuple)) else (out,)
                for i, o in zip(donate_argnums, outs):
                    args[i] = o
            args = tuple(args)
    observe(rec.label, best)   # rec.label, not label: a same-named but
    # different program was routed to its own `label#N` record
    result = rec.as_dict()
    result["wall_time_s"] = best
    return result


# -- the attribution table --------------------------------------------------

def _fmt(v, spec="{:.3g}", na="-"):
    return na if v is None else spec.format(v)


def report(top: int = 30) -> str:
    """Ranked attribution table (merged into ``Profiler.summary()``):
    programs/segments by total synced wall time, each with its roofline
    classification, MFU, and achieved-vs-optimal ratio.  The row with
    the smallest ach/opt ratio is the next optimization target; rows
    whose backend returned no analysis read 'unavailable' instead of a
    fabricated MFU."""
    recs = [r for r in records() if r.calls or r.cost or r.memory]
    if not recs:
        return ""
    chip = chip_spec()
    recs.sort(key=lambda r: -r.total_s)
    lines = [
        f"perf attribution vs {chip.name} "
        f"({chip.peak_flops/1e12:.1f} TFLOP/s, {chip.hbm_bw/1e9:.0f} GB/s,"
        f" ridge {chip.ridge:.1f} flop/B); overall mfu "
        f"{_overall_mfu()*100:.2f}%",
        f"  {'program/segment':28s} {'calls':>6s} {'best_ms':>9s} "
        f"{'gflop':>8s} {'gb':>7s} {'bound':>8s} {'mfu%':>7s} "
        f"{'opt_ms':>8s} {'ach/opt':>8s} {'hbm_room':>8s}",
    ]
    worst = None
    for r in recs[:top]:
        if not r.available:
            wall = _fmt(r.best_s and r.best_s * 1e3, "{:9.3f}", " " * 9)
            lines.append(
                f"  {r.label[:28]:28s} {r.calls:6d} {wall:>9s} "
                f"{'analysis ' + UNAVAILABLE:>42s}")
            continue
        ratio = r.achieved_vs_optimal(chip)
        if ratio is not None and (worst is None or ratio < worst[1]):
            worst = (r.label, ratio)
        mfu = r.mfu(chip)
        lines.append(
            "  {:28s} {:6d} {:>9s} {:>8s} {:>7s} {:>8s} {:>7s} {:>8s} "
            "{:>8s} {:>8s}".format(
                r.label[:28], r.calls,
                _fmt(r.best_s and r.best_s * 1e3, "{:.3f}"),
                _fmt(r.flops and r.flops / 1e9, "{:.2f}"),
                _fmt(r.bytes_accessed and r.bytes_accessed / 1e9,
                     "{:.3f}"),
                r.bound(chip),
                _fmt(mfu and mfu * 100, "{:.2f}"),
                _fmt(r.optimal_s(chip) and r.optimal_s(chip) * 1e3,
                     "{:.3f}"),
                _fmt(ratio, "{:.3f}"),
                _fmt(r.hbm_headroom(chip), "{:.1f}x")))
    if worst is not None:
        lines.append(f"  worst achieved-vs-optimal: {worst[0]} "
                     f"({worst[1]:.3f} of roofline)")
    return "\n".join(lines)


def hlo_report(fn=None, top: int = 10) -> str:
    """The program microscope (ISSUE 12): per-instruction attribution of
    a captured program's optimized HLO — top-k entry instructions (the
    units XLA dispatches) ranked by roofline-model time, fusions called
    out with their estimated flops/bytes.

    ``fn`` may be a perf-record label string, a ``jit.CompiledFunction``
    (its perf label is used), any callable (``__name__``), or None for
    every captured program concatenated.  Programs are captured on the
    same PTPU_PERF AOT path as the cost analyses; a program whose HLO
    text failed to parse renders as 'unavailable' — never invented
    numbers."""
    from . import hlo as _hlo

    if fn is None:
        parts = [_hlo.report(lb, top=top) for lb in _hlo.labels()]
        return "\n".join(p for p in parts if p)
    if isinstance(fn, str):
        label = fn
    elif hasattr(fn, "_perf_label"):
        label = fn._perf_label()
    else:
        label = getattr(fn, "__name__", str(fn))
    return _hlo.report(label, top=top)
