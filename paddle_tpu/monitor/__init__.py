"""Runtime telemetry registry (reference: paddle/fluid/platform/monitor.h
StatRegistry + STAT_INT gauges).

The reference pairs its tracer (HostTracer/CudaTracer) with an always-on
stats layer; this module is that layer for paddle_tpu. A process-wide
`StatRegistry` holds typed metrics — monotonic `Counter`s, last-value
`Gauge`s (optionally backed by a callback), and bucketed `Histogram`s —
each of which can fan out into labeled series (`metric.labels(k=v)`).

Design constraints, in priority order:

- **near-zero cost when idle**: every mutation checks one module-level
  flag first; with `PTPU_MONITOR=0` an increment is a no-op function call
  (sub-µs, guarded by tests/test_monitor.py::test_disabled_overhead_guard).
- **no jax dependency**: this file is pure stdlib so importing it never
  initializes an accelerator backend (device gauges are injected from
  paddle_tpu.device as callbacks); the profiler, launcher children, and
  export tooling can all use it headlessly.
- **thread-safe**: hot paths run from DataLoader workers and the autograd
  engine; each metric guards its state with its own lock.

Exporters: `export_prometheus()` (text exposition format),
`export_jsonl(path)` (append one timestamped snapshot per call — a
time-series when called per step/epoch), and `snapshot()` (plain dict,
merged into `Profiler.summary()`).

Naming convention: `subsystem/metric` (e.g. ``pipeline/stage_time``);
slashes are mapped to ``_`` for Prometheus.
"""
from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "StatRegistry", "get_registry",
    "counter", "gauge", "histogram", "snapshot", "export_prometheus",
    "export_jsonl", "render", "reset", "enabled", "enable", "refresh",
    "timer", "STAT_ADD", "STAT_SUB", "STAT_RESET",
    "exemplars_enabled", "enable_exemplars",
]


def _env_enabled() -> bool:
    return os.environ.get("PTPU_MONITOR", "1").strip().lower() not in (
        "0", "false", "off", "")


# Module-level flag, NOT per-registry: the disabled fast path must be one
# global read + branch, no attribute chains.
_enabled = _env_enabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip collection on/off at runtime (overrides PTPU_MONITOR)."""
    global _enabled
    _enabled = bool(on)


def refresh():
    """Re-read PTPU_MONITOR (+ PTPU_EXEMPLARS) from the environment."""
    global _enabled, _exemplars
    _enabled = _env_enabled()
    _exemplars = _env_exemplars()


# -- histogram exemplars (ISSUE 16) -----------------------------------------
# Opt-in on top of PTPU_MONITOR: when on, Histogram.observe(v, trace_id=)
# stamps the observation's trace id on the bucket it lands in, rendered
# in OpenMetrics exemplar syntax on /metrics — the link from "p99 ttft
# spiked" to the kept tail-sampled trace that caused it.  One slot per
# bucket (newest wins): bounded, no per-observation allocation growth.

def _env_exemplars() -> bool:
    return os.environ.get("PTPU_EXEMPLARS", "0").strip().lower() not in (
        "0", "false", "off", "")


_exemplars = _env_exemplars()


def exemplars_enabled() -> bool:
    return _exemplars


def enable_exemplars(on: bool = True):
    """Flip exemplar capture on/off at runtime (overrides PTPU_EXEMPLARS)."""
    global _exemplars
    _exemplars = bool(on)


def _coerce(v):
    """Resolve a stored value to a plain float. Gauges may hold lazy device
    scalars (e.g. an un-synced grad-norm); float() forces them only at
    snapshot/export time, keeping the recording site async."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


class _Metric:
    """Base: name, own value state, and an optional family of labeled
    children (one child per unique label set, created on demand)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: dict = {}   # sorted (k, v) tuple -> child metric
        self._label_key = ()
        self._touched = False

    def labels(self, **labels):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    child._label_key = key
                    self._children[key] = child
        return child

    def _make_child(self):
        raise NotImplementedError

    # -- introspection ----------------------------------------------------
    def _series(self):
        """[(label_key_tuple, metric)] for every live series. Children are
        copied under the lock so concurrent labels() registration can't
        mutate the dict mid-iteration."""
        with self._lock:
            children = sorted(self._children.items())
            touched = self._touched
        out = []
        if touched:
            out.append(((), self))
        out.extend(children)
        return out

    def _snapshot_value(self):
        raise NotImplementedError

    def snapshot(self):
        """Value for an unlabeled metric; {"k=v,...": value} when labeled."""
        with self._lock:
            children = sorted(self._children.items())
            touched = self._touched
        if not children:
            return self._snapshot_value()
        out = {}
        if touched:
            out[""] = self._snapshot_value()
        for key, child in children:
            out[",".join(f"{k}={v}" for k, v in key)] = child._snapshot_value()
        return out

    def _reset(self):
        with self._lock:
            children = list(self._children.values())
            self._touched = False
            self._zero()
        # zero children IN PLACE (don't drop them): labeled handles cached
        # at call sites must keep feeding the registry after reset()
        for c in children:
            c._reset()

    def _zero(self):
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic count (reference STAT_INT used as an accumulator)."""

    kind = "counter"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._value = 0.0

    def _make_child(self):
        return Counter(self.name, self.help)

    def inc(self, n=1):
        return self.add(n)

    def add(self, n=1):
        if not _enabled:
            return self
        n = float(n)
        with self._lock:
            self._value += n
            self._touched = True
        return self

    @property
    def value(self):
        return self._value

    def _snapshot_value(self):
        return self._value

    def _zero(self):
        self._value = 0.0


class Gauge(_Metric):
    """Last-written value, or a live callback (fn) sampled at export time
    (how device-memory watermarks are wired in without a jax import here)."""

    kind = "gauge"

    def __init__(self, name, help="", fn=None):
        super().__init__(name, help)
        self._value = 0.0
        self._fn = fn
        if fn is not None:
            self._touched = True

    def _make_child(self):
        return Gauge(self.name, self.help)

    def set(self, v):
        if not _enabled:
            return self
        with self._lock:
            self._value = v          # may be a lazy device scalar
            self._touched = True
        return self

    def add(self, n=1.0):
        if not _enabled:
            return self
        with self._lock:
            self._value = _coerce(self._value) + float(n)
            self._touched = True
        return self

    def sub(self, n=1.0):
        return self.add(-float(n))

    @property
    def value(self):
        if self._fn is not None:
            try:
                return _coerce(self._fn())
            except Exception:
                # a broken callback (a device gauge probing a torn-down
                # backend, say) must not take down snapshot()/render —
                # count it so the breakage is visible, keep exporting
                _default.counter(
                    "monitor/gauge_errors",
                    "gauge callbacks that raised at sample time",
                ).labels(name=self.name).inc()
                return 0.0
        return _coerce(self._value)

    def _snapshot_value(self):
        return self.value

    def _zero(self):
        self._value = 0.0
        if self._fn is not None:
            self._touched = True   # callback gauges stay live across reset()


# Two buckets per decade spanning µs-scale timings to token counts; override
# per-metric via histogram(name, buckets=...).
DEFAULT_BUCKETS = tuple(
    float(f"{b}e{e}") for e in range(-6, 7) for b in (1, 3))


def _interp_percentile(q, buckets, counts, count, mn, mx):
    """q-th percentile (q in [0, 100]) linearly interpolated inside the
    bucket holding the target rank; the observed min/max clamp the first
    and last occupied buckets, so a single-bucket histogram still
    returns a value inside the data's actual range."""
    if not count:
        return 0.0
    q = min(max(float(q), 0.0), 100.0)
    target = q / 100.0 * count
    if target <= 0:
        return mn
    cum = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        prev = cum
        cum += c
        if cum >= target:
            lo = buckets[i - 1] if i > 0 else mn
            hi = buckets[i] if i < len(buckets) else mx
            lo = max(min(lo, mx), mn)
            hi = max(min(hi, mx), lo)
            return lo + (target - prev) / c * (hi - lo)
    return mx


class Histogram(_Metric):
    """Bucketed distribution with count/sum/min/max running stats."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=None):
        super().__init__(name, help)
        self._buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._zero()

    def _make_child(self):
        return Histogram(self.name, self.help, self._buckets)

    def observe(self, v, trace_id=None):
        if not _enabled:
            return self
        v = float(v)
        with self._lock:
            i = bisect.bisect_left(self._buckets, v)
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            self._min = v if self._count == 1 else min(self._min, v)
            self._max = v if self._count == 1 else max(self._max, v)
            self._touched = True
            if _exemplars and trace_id:
                if self._exm is None:
                    self._exm = [None] * len(self._counts)
                self._exm[i] = (str(trace_id), v, time.time())
        return self

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q) -> float:
        """q-th percentile (q in [0, 100]) interpolated from the bucket
        counts — how `serving/ttft` p99 is read without storing samples."""
        with self._lock:
            return _interp_percentile(q, self._buckets, self._counts,
                                      self._count, self._min, self._max)

    def _snapshot_value(self):
        with self._lock:   # consistent (count, sum, min, max) tuple
            if not self._count:
                return {"count": 0, "sum": 0.0}
            out = {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "avg": self._sum / self._count,
            }
            for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
                out[key] = _interp_percentile(
                    q, self._buckets, self._counts, self._count,
                    self._min, self._max)
            return out

    def _bucket_rows(self):
        """Consistent (buckets, per-bucket counts, count, sum, exemplars)
        copy — exemplars is None until one was ever stamped."""
        with self._lock:
            return (self._buckets, list(self._counts), self._count,
                    self._sum,
                    None if self._exm is None else list(self._exm))

    def _merge_buckets(self, buckets, counts, count, sum_, exemplars=None):
        """Merge another histogram's raw bucket state into this one —
        the fleet-federation path (counts parsed back from a replica's
        exposition).  Bucket BOUNDS must match exactly: replicas run the
        same code so they share bounds; a mismatch is a config bug and
        raises rather than silently mis-binning.

        min/max are reconstructed from the occupied bucket edges (the
        exposition does not carry them), so percentiles recomputed from
        a merged histogram interpolate inside edge-clamped buckets —
        exact bucket/count/sum round-trip, approximate range clamp."""
        buckets = tuple(buckets)
        if buckets != self._buckets:
            raise ValueError(
                f"histogram {self.name!r}: merge with different bucket "
                f"bounds ({len(buckets)} vs {len(self._buckets)} edges) "
                "— replicas must share bucket bounds")
        counts = list(counts)
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name!r}: merge with {len(counts)} "
                f"bucket counts, expected {len(self._counts)}")
        with self._lock:
            occupied = [i for i, c in enumerate(counts) if c]
            if occupied:
                lo = buckets[occupied[0] - 1] if occupied[0] > 0 else 0.0
                if occupied[-1] < len(buckets):
                    hi = buckets[occupied[-1]]
                else:   # overflow bucket: upper edge unknown — the mean
                    # is the only bound the exposition still carries
                    hi = max(buckets[-1], sum_ / max(count, 1))
                if self._count == 0:
                    self._min, self._max = lo, hi
                else:
                    self._min = min(self._min, lo)
                    self._max = max(self._max, hi)
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += count
            self._sum += sum_
            # exemplars survive federation: newest-by-timestamp wins per
            # bucket (bypasses the PTPU_EXEMPLARS gate like every other
            # merge write — this is reconstruction, not instrumentation)
            if exemplars:
                if self._exm is None:
                    self._exm = [None] * len(self._counts)
                for i, ex in enumerate(exemplars[:len(self._exm)]):
                    if ex is None:
                        continue
                    cur = self._exm[i]
                    if cur is None or ex[2] >= cur[2]:
                        self._exm[i] = (str(ex[0]), float(ex[1]),
                                        float(ex[2]))
            self._touched = True
        return self

    def _zero(self):
        self._counts = [0] * (len(self._buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = 0.0
        self._max = 0.0
        self._exm = None   # per-bucket (trace_id, value, ts), lazy


_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _NAME_BAD.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n or "_"


def _prom_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_labels(key, extra=()):
    items = list(key) + list(extra)
    if not items:
        return ""
    return "{" + ",".join(
        f'{_prom_name(k)}="{_prom_label_value(str(v))}"' for k, v in items
    ) + "}"


def _prom_num(v) -> str:
    v = _coerce(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _exemplar_suffix(ex) -> str:
    """OpenMetrics exemplar rendering for one bucket line:
    `` # {trace_id="..."} <value> <unix_ts>``."""
    tid, v, ts = ex
    return (f' # {{trace_id="{_prom_label_value(str(tid))}"}} '
            f"{_prom_num(v)} {repr(float(ts))}")


class StatRegistry:
    """Named metric store (reference monitor.h StatRegistry::Instance)."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    # -- registration (get-or-create, type-checked) -----------------------
    def _get_or_create(self, cls, name, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m

    def counter(self, name, help="") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(self, name, help="", fn=None) -> Gauge:
        g = self._get_or_create(Gauge, name, help=help)
        if fn is not None:
            g._fn = fn
            g._touched = True
        return g

    def histogram(self, name, help="", buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help=help, buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def names(self):
        return sorted(self._metrics)

    def reset(self):
        """Zero every metric IN PLACE. Registration (and callback fns)
        survive — including labeled children — so series handles cached at
        call sites stay live."""
        for _, m in self._items():
            m._reset()

    # -- exporters --------------------------------------------------------
    def _items(self):
        """Sorted (name, metric) pairs, copied under the registry lock so
        concurrent registration can't mutate the dict mid-export."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self) -> dict:
        """{name: value | hist-stats | {label_str: ...}} for every metric
        with at least one live series."""
        out = {}
        for name, m in self._items():
            if m._touched or m._children:
                out[name] = m.snapshot()
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines = []
        for name, m in self._items():
            series = m._series()
            if not series:
                continue
            pname = _prom_name(m.name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            for key, s in series:
                if isinstance(s, Histogram):
                    buckets, counts, count, total, exm = s._bucket_rows()
                    cum = 0
                    for i, (le, c) in enumerate(zip(buckets, counts)):
                        cum += c
                        line = (f"{pname}_bucket"
                                f"{_prom_labels(key, [('le', repr(le))])}"
                                f" {cum}")
                        if exm is not None and exm[i] is not None:
                            line += _exemplar_suffix(exm[i])
                        lines.append(line)
                    line = (f"{pname}_bucket"
                            f"{_prom_labels(key, [('le', '+Inf')])}"
                            f" {count}")
                    if exm is not None and exm[len(buckets)] is not None:
                        line += _exemplar_suffix(exm[len(buckets)])
                    lines.append(line)
                    lines.append(
                        f"{pname}_sum{_prom_labels(key)} {_prom_num(total)}")
                    lines.append(
                        f"{pname}_count{_prom_labels(key)} {count}")
                else:
                    val = s.value if isinstance(s, Gauge) else s._value
                    lines.append(
                        f"{pname}{_prom_labels(key)} {_prom_num(val)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export_jsonl(self, path: str) -> dict:
        """Append one timestamped snapshot line; returns the record."""
        rec = {"ts": time.time(), "metrics": self.snapshot()}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec

    def merge_snapshot(self, parsed, labels=None) -> "StatRegistry":
        """Merge a PARSED exposition (``fleet.parse_prometheus`` output:
        {name: {"kind", "help", "series": {label_key: value}}}) into this
        registry — the metrics-federation primitive:

        - **counters sum**: each source series accumulates into the
          series with its ORIGINAL labels, so merging N replicas leaves
          the original series holding the fleet-wide total;
        - **gauges keep per-source values**: only the ``labels``-tagged
          copy is written (no meaningful way to sum a gauge);
        - **histograms merge buckets**: per-bucket counts/count/sum add
          into the original series (bounds must match), percentiles are
          then recomputed from the merged buckets on read.

        ``labels`` (e.g. ``{"replica": "r0"}``) additionally records
        every source series under its original labels + these, so the
        fleet exposition carries both the total and the per-replica
        breakdown.  Mutations bypass the PTPU_MONITOR gate: this is
        reconstruction of already-collected data, not hot-path
        instrumentation."""
        extra = dict(labels or {})

        def _bump(metric, key, v):
            tgt = metric if not key else metric.labels(**dict(key))
            with tgt._lock:
                tgt._value = tgt._value + v if metric.kind == "counter" \
                    else v
                tgt._touched = True

        for name, pm in parsed.items():
            kind = pm.get("kind", "gauge")
            help_ = pm.get("help", "")
            series = sorted(pm.get("series", {}).items())
            if kind == "counter":
                c = self.counter(name, help_)
                for key, v in series:
                    _bump(c, key, v)
                    if extra:
                        _bump(c, tuple(sorted(
                            dict(key, **extra).items())), v)
            elif kind == "histogram":
                h = None
                for key, hv in series:
                    if h is None:
                        h = self.histogram(name, help_,
                                           buckets=hv["buckets"])
                    tgt = h if not key else h.labels(**dict(key))
                    tgt._merge_buckets(hv["buckets"], hv["counts"],
                                       hv["count"], hv["sum"],
                                       exemplars=hv.get("exemplars"))
                    if extra:
                        h.labels(**dict(key, **extra))._merge_buckets(
                            hv["buckets"], hv["counts"], hv["count"],
                            hv["sum"], exemplars=hv.get("exemplars"))
            else:   # gauge / untyped: per-source value only
                g = self.gauge(name, help_)
                for key, v in series:
                    if extra:
                        _bump(g, tuple(sorted(
                            dict(key, **extra).items())), v)
                    else:
                        _bump(g, key, v)
        return self

    def render(self) -> str:
        """Human-readable table of the snapshot (Profiler.summary section)."""
        snap = self.snapshot()
        if not snap:
            return ""
        lines = [f"{'runtime monitor':48s} {'value':>24s}"]

        def fmt(v):
            if isinstance(v, dict) and "count" in v:
                if not v["count"]:
                    return "n=0"
                out = f"n={v['count']} avg={v['avg']:.4g}"
                if "p50" in v:
                    out += f" p50={v['p50']:.4g} p95={v['p95']:.4g}"
                return out + f" max={v['max']:.4g}"
            return f"{_coerce(v):.6g}"

        for name, val in snap.items():
            if isinstance(val, dict) and "count" not in val:
                for lab, v in val.items():
                    tag = f"{name}{{{lab}}}" if lab else name
                    lines.append(f"  {tag[:46]:46s} {fmt(v):>24s}")
            else:
                lines.append(f"  {name[:46]:46s} {fmt(val):>24s}")
        return "\n".join(lines)


_default = StatRegistry()


def get_registry() -> StatRegistry:
    return _default


def counter(name, help="") -> Counter:
    return _default.counter(name, help=help)


def gauge(name, help="", fn=None) -> Gauge:
    return _default.gauge(name, help=help, fn=fn)


def histogram(name, help="", buckets=None) -> Histogram:
    return _default.histogram(name, help=help, buckets=buckets)


def snapshot() -> dict:
    return _default.snapshot()


def export_prometheus() -> str:
    return _default.export_prometheus()


def export_jsonl(path) -> dict:
    return _default.export_jsonl(path)


def render() -> str:
    return _default.render()


def reset():
    _default.reset()


class timer:
    """Context manager observing elapsed seconds into a histogram:

        with monitor.timer("pipeline/stage_time"):
            run()
    """

    def __init__(self, name_or_hist, **labels):
        self._t0 = None
        self._hist = None
        if not _enabled:   # no phantom series registration when disabled
            return
        if isinstance(name_or_hist, Histogram):
            self._hist = name_or_hist
        else:
            self._hist = _default.histogram(name_or_hist)
        if labels:
            self._hist = self._hist.labels(**labels)

    def __enter__(self):
        if _enabled and self._hist is not None:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            self._hist.observe(time.perf_counter() - self._t0)
        return False


# -- reference monitor.h macro parity ------------------------------------
def STAT_ADD(name, value):
    """STAT_ADD(item, t): add to the named int stat (gauge semantics)."""
    _default.gauge(name).add(value)


def STAT_SUB(name, value):
    _default.gauge(name).sub(value)


def STAT_RESET(name):
    _default.gauge(name).set(0)


# -- v2+: tracing / flight recorder / live endpoint / perf attribution /
# fleet federation / HLO microscope / training microscope ------------------
# Metric inventory by wing: serving/serving-perf series are documented in
# perf.py and hlo.py, fleet federation in fleet.py, and the v6 training
# wings (train/loss*, train/grad_norm{layer}, train/goodput_examples_per_s,
# train/data_wait_frac, train/step_time, reader/wait_time,
# collective/time{kind}, resilience/nonfinite{layer,which},
# fleet/straggler*) in train.py's module docstring.
# Guarded relative imports: tests load THIS file standalone (spec_from_
# file_location, no package) to prove the core registry is jax-free; in
# that mode the v2 submodules — equally stdlib-only — are simply absent.
try:
    from . import trace, flight, serve, perf, fleet, hlo, train  # noqa: E402,F401
    from . import reqlog, slo, memory             # noqa: E402,F401
    from .flight import watchdog                  # noqa: E402,F401
    from .serve import start_server, stop_server  # noqa: E402,F401

    __all__ += ["trace", "flight", "serve", "perf", "fleet", "hlo",
                "train", "reqlog", "slo", "memory", "watchdog",
                "start_server", "stop_server"]
except ImportError:   # standalone module load — core registry only
    pass
