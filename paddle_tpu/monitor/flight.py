"""Flight recorder — the post-mortem story for crashes, preemptions, and
distributed hangs.

A fixed-size, lock-cheap ring buffer keeps the last N observability
records of this process (finished trace spans, plus explicit
``flight.note(...)`` breadcrumbs).  On the events below, the ring — with
a monitor snapshot and optionally a py-stack of every live thread — is
dumped as one JSON file into ``PTPU_FLIGHT_DIR``:

- ``install()``-ed signals (SIGTERM/SIGABRT by default; handlers CHAIN
  to whatever was installed before, so a PreemptionHandler or the
  default death still runs after the dump);
- an unhandled exception (``sys.excepthook`` wrapper);
- ``resilience.PreemptionHandler`` preemption (wired via
  :func:`maybe_dump`, active whenever ``PTPU_FLIGHT_DIR`` is set);
- the :func:`watchdog` thread: when no span/step has completed for
  ``stall_s`` seconds (``trace.heartbeat()`` is the liveness signal, fed
  by span ends and by the engine/StepGuard step loops directly), the
  ring plus a stack snapshot of ALL threads is dumped — what you read
  the morning after a distributed hang.

Ring size: ``PTPU_FLIGHT_RING`` (default 512 records).  Everything here
is stdlib-only; the monitor snapshot is imported lazily at dump time.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

__all__ = [
    "FlightRecorder", "get_recorder", "record_span", "note", "dump",
    "maybe_dump", "dump_from_signal", "install", "uninstall", "watchdog",
    "Watchdog", "flight_dir", "latest_dump",
]

_DEFAULT_RING = 512


def flight_dir():
    """PTPU_FLIGHT_DIR, or None (None disables the automatic dumps —
    explicit ``dump(dir=...)`` still works)."""
    d = os.environ.get("PTPU_FLIGHT_DIR", "").strip()
    return d or None


class FlightRecorder:
    """Bounded ring of observability records.  Append is one deque.append
    under a lock (no allocation beyond the record itself); the ring is
    only serialized at dump time."""

    def __init__(self, maxlen=None):
        if maxlen is None:
            maxlen = int(os.environ.get("PTPU_FLIGHT_RING",
                                        str(_DEFAULT_RING)))
        self.maxlen = int(maxlen)
        self._ring = deque(maxlen=self.maxlen)
        self._lock = threading.Lock()
        self._dumps = 0

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    # -- dumping ------------------------------------------------------------

    def dump(self, reason: str, dir: str = None, with_stacks: bool = True,
             extra: dict = None) -> str:
        """Write one self-contained post-mortem JSON; returns its path.
        `dir` defaults to PTPU_FLIGHT_DIR, then <tmp>/ptpu_flight."""
        import tempfile

        from . import snapshot, trace

        dir = dir or flight_dir() or os.path.join(tempfile.gettempdir(),
                                                  "ptpu_flight")
        os.makedirs(dir, exist_ok=True)
        self._dumps += 1
        doc = {
            "version": 1,
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "last_activity_age_s": trace.last_activity_age(),
            "ring": self.records(),
            "metrics": _safe_snapshot(snapshot),
        }
        if extra:
            doc["extra"] = extra
        if with_stacks:
            doc["stacks"] = _thread_stacks()
        path = os.path.join(
            dir, f"flight_{os.getpid()}_{reason}_{self._dumps:03d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)   # a reader never sees a half-written dump
        return path


def _safe_snapshot(snapshot_fn) -> dict:
    """A dump must succeed even when a metric holds an unserializable
    lazy value — post-mortems run at the worst moments by definition."""
    try:
        return json.loads(json.dumps(snapshot_fn(), default=str))
    except Exception as e:   # ptpu-check[silent-except]: the flight dump is last-resort
        # diagnostics — a snapshot failure is itself recorded, not raised
        return {"_snapshot_error": repr(e)}


def _thread_stacks() -> dict:
    """Formatted py-stack of every live thread (the faulthandler story,
    but JSON-structured and name-annotated)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        out[f"{tid} ({names.get(tid, '?')})"] = [
            ln.rstrip("\n") for ln in traceback.format_stack(frame)]
    return out


_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def record_span(span_dict: dict) -> None:
    """Called by trace.Span.end for every finished span."""
    _recorder.record({"kind": "span", **span_dict})


def note(event: str, **payload) -> None:
    """Explicit breadcrumb (state transitions that aren't spans)."""
    _recorder.record({"kind": "note", "event": event, "ts": time.time(),
                      **payload})


def dump(reason: str, dir: str = None, with_stacks: bool = True,
         extra: dict = None) -> str:
    return _recorder.dump(reason, dir=dir, with_stacks=with_stacks,
                          extra=extra)


def latest_dump(dir: str = None) -> "str | None":
    """Path of the newest flight dump in `dir` (default PTPU_FLIGHT_DIR),
    or None when the dir is unset/missing/empty.  Backs the
    ``/flight/latest`` endpoint the fleet aggregator harvests from —
    newest by mtime, .tmp staging files excluded (the atomic-rename
    commit means every visible flight_*.json is complete)."""
    dir = dir or flight_dir()
    if not dir:
        return None
    try:
        names = [n for n in os.listdir(dir)
                 if n.startswith("flight_") and n.endswith(".json")]
    except OSError:
        return None
    best, best_m = None, None
    for n in names:
        p = os.path.join(dir, n)
        try:
            m = os.path.getmtime(p)
        except OSError:   # raced a cleanup — skip, not fatal
            continue
        if best_m is None or m > best_m:
            best, best_m = p, m
    return best


def maybe_dump(reason: str, extra: dict = None):
    """Dump only when PTPU_FLIGHT_DIR is configured — the opt-in form
    the automatic hooks use."""
    if flight_dir() is None:
        return None
    try:
        return dump(reason, extra=extra)
    except Exception:   # ptpu-check[silent-except]: a failed post-mortem write (disk
        # full, dir gone) must never mask the signal/exception being
        # handled — the process is already dying
        return None


def dump_from_signal(reason: str, extra: dict = None,
                     timeout: float = 5.0):
    """Best-effort dump for SIGNAL handlers.  A handler runs on the main
    thread BETWEEN bytecodes — the interrupted frame may be holding a
    metric/ring `threading.Lock` (non-reentrant), so dumping inline could
    self-deadlock the process instead of letting it die/checkpoint.  The
    dump therefore runs on a helper thread with a bounded join: a held
    lock costs (at most) this dump, never the signal's disposition."""
    if flight_dir() is None:
        return None
    out = []
    t = threading.Thread(
        target=lambda: out.append(maybe_dump(reason, extra=extra)),
        name="ptpu-flight-dump", daemon=True)
    t.start()
    t.join(timeout)
    return out[0] if out else None


# -- signal / excepthook wiring --------------------------------------------
_prev_handlers: dict = {}
_prev_excepthook = None


def _on_signal(signum, frame):
    dump_from_signal(signal.Signals(signum).name.lower())
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        # restore + re-deliver so the default disposition (death) runs
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    # SIG_IGN / None: swallow, matching the previous disposition


def _on_exception(etype, evalue, tb):
    maybe_dump("exception", extra={
        "exception": "".join(
            traceback.format_exception_only(etype, evalue)).strip()})
    if _prev_excepthook is not None:
        _prev_excepthook(etype, evalue, tb)


def install(signals=(signal.SIGTERM, signal.SIGABRT),
            exceptions: bool = True) -> None:
    """Arm the dump-on-death hooks (idempotent; main thread only, the
    signal-module restriction).  Dumps fire only when PTPU_FLIGHT_DIR is
    set, so installing is safe unconditionally."""
    global _prev_excepthook
    for sig in signals:
        if sig in _prev_handlers:
            continue
        _prev_handlers[sig] = signal.signal(sig, _on_signal)
    if exceptions and _prev_excepthook is None:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _on_exception


def uninstall() -> None:
    global _prev_excepthook
    for sig, prev in list(_prev_handlers.items()):
        try:
            signal.signal(sig, prev)
        except (ValueError, TypeError):   # non-main-thread teardown
            pass
    _prev_handlers.clear()
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None


# -- the watchdog -----------------------------------------------------------

class Watchdog(threading.Thread):
    """Daemon thread dumping the ring + all-thread stacks when no
    span/step has completed for `stall_s` seconds, then re-arming (one
    dump per distinct stall, not one per poll).  Counts
    ``monitor/watchdog_dumps``."""

    def __init__(self, stall_s: float, dir: str = None, interval=None):
        super().__init__(name="ptpu-watchdog", daemon=True)
        self.stall_s = float(stall_s)
        self.dir = dir
        self.interval = interval or max(0.05, self.stall_s / 4.0)
        self.dump_paths: list = []
        self._stop_evt = threading.Event()

    def run(self):
        from . import counter, trace

        ctr = counter("monitor/watchdog_dumps",
                      "flight dumps triggered by a detected stall")
        errs = counter("monitor/watchdog_errors",
                       "watchdog dump attempts that failed")
        dumped_beat = None
        while not self._stop_evt.wait(self.interval):
            age = trace.last_activity_age()
            if age <= self.stall_s:
                continue
            # re-arm by remembering WHICH beat we dumped at (one dump per
            # distinct stall), NOT by calling trace.heartbeat(): forging
            # a beat would reset /healthz's last_activity_age_s and hide
            # an ongoing stall from the fleet rollup (ISSUE 11 — the
            # aggregator classifies `stalled` off exactly that field)
            beat = trace._last_beat[0]
            if beat == dumped_beat:
                continue
            try:
                path = _recorder.dump(
                    "stall", dir=self.dir,
                    extra={"stall_s": self.stall_s, "stalled_for_s": age})
                self.dump_paths.append(path)
                ctr.inc()
            except Exception:   # ptpu-check[silent-except]: a failed dump (disk full,
                # dir gone) must not kill the watchdog thread — the NEXT
                # stall still deserves an attempt; failures are counted
                errs.inc()
            dumped_beat = beat   # next dump needs a NEW stall

    def stop(self, timeout: float = 5.0):
        self._stop_evt.set()
        self.join(timeout)


def watchdog(stall_s: float, dir: str = None, interval=None) -> Watchdog:
    """Start a stall watchdog; returns the (stoppable) thread::

        w = monitor.watchdog(stall_s=120)   # training-step scale
        ...
        w.stop()
    """
    from . import trace

    trace.heartbeat()   # the clock starts now, not at module import
    w = Watchdog(stall_s, dir=dir, interval=interval)
    w.start()
    return w
