"""The ONE declared wire-protocol registry (ISSUE 14).

Every cross-process surface this framework speaks — the rpc frame, the
``/healthz`` document, the fleet router feed — is versioned HERE, and
``ptpu-check``'s ``wire-compat`` rule statically checks the implementing
modules against these declarations.  The PR-9/PR-10 review rounds each
fixed a version-skew hazard by hand (legacy 3-tuple rpc frames, healthz
``schema_version`` bumps, accrete-only router-feed keys); with the
registry, drifting one side without the other is a lint failure instead
of a deploy incident.

Rules of the road (enforced by convention + lint, in matching order):

- **rpc frame**: a pickled tuple.  Arity must stay within
  ``[RPC_FRAME_MIN, RPC_FRAME_MAX]`` — the receiver slices the first
  ``RPC_FRAME_MIN`` mandatory fields and treats the rest as optional,
  so an old server keeps accepting a new client's frame ONLY while the
  new fields stay beyond the mandatory slice.  Growing the frame means
  bumping ``RPC_FRAME_MAX`` here first.
- **/healthz**: ``schema_version`` only ever INCREASES and keys only
  ever accrete (PR-5 consumers stay byte-compatible).  The per-replica
  document and the fleet rollup version independently.
- **router feed**: the per-replica dict ``fleet.FleetAggregator
  .snapshot()`` hands the load-aware router.  Keys only accrete; a
  replica predating a key reads ``None``, never ``KeyError``.  The
  canonical builder carries a ``# ptpu-wire: router-feed`` anchor and
  must emit EXACTLY these keys.
- **reqlog event**: the wide per-request event ``monitor/reqlog.py``
  emits (ISSUE 16).  Same accrete-only contract as the feed; the
  canonical builder carries a ``# ptpu-wire: reqlog-event`` anchor.
- **router protocol** (ISSUE 17): the request frames the multi-replica
  router and its replica workers exchange over ``distributed/rpc.py``
  (submit / result / KV handoff / poll), plus the router's exported
  metric-name set.  Same accrete-only contract; the canonical builders
  in ``serving/router.py`` carry ``# ptpu-wire: router-submit`` /
  ``router-result`` / ``router-handoff`` / ``router-poll`` /
  ``router-metrics`` anchors.

stdlib-only, import-light: both ``monitor`` (serve/fleet) and
``distributed.rpc`` import this module at startup.
"""
from __future__ import annotations

__all__ = ["RPC_FRAME_MIN", "RPC_FRAME_MAX", "HEALTHZ_SCHEMA_VERSION",
           "FLEET_HEALTHZ_SCHEMA_VERSION", "ROUTER_FEED_KEYS",
           "REQLOG_SCHEMA_VERSION", "REQLOG_EVENT_KEYS",
           "ROUTER_SCHEMA_VERSION", "ROUTER_SUBMIT_KEYS",
           "ROUTER_RESULT_KEYS", "ROUTER_HANDOFF_KEYS",
           "ROUTER_POLL_KEYS", "ROUTER_METRIC_NAMES",
           "API_ERROR_KEYS"]

# rpc wire frame: (fn, args, kwargs[, trace_hdr]) — the legacy 3-tuple
# is still accepted by every server (PR-9's mid-deploy contract)
RPC_FRAME_MIN = 3
RPC_FRAME_MAX = 4

# /healthz per-replica document (monitor/serve.py): v3 = PR-10's process
# identity (rss_bytes, open_fds) on top of v2's host/rank/replica_id
HEALTHZ_SCHEMA_VERSION = 3

# /fleet/healthz rollup (monitor/fleet.py): v2 = PR-11's straggler block
FLEET_HEALTHZ_SCHEMA_VERSION = 2

# the load-aware-routing feed: FleetAggregator.snapshot()'s per-replica
# keys, in emission order.  Accrete-only — removing or renaming one is a
# wire break for every router built on the feed.
ROUTER_FEED_KEYS = (
    "url",
    "state",
    "host",
    "pid",
    "queue_depth",
    "running",
    "waiting",
    "decode_tokens_per_s",
    "goodput_tokens_per_s",
    "padding_waste_rows",
    "kernels_per_step",
    "step_time",
    "goodput_examples_per_s",
    "data_wait_frac",
    "straggler_skew",
    "rss_bytes",
    "open_fds",
    "uptime_s",
    "last_activity_age_s",
    "scrape_age_s",
    "scrape_errors",
    "fail_streak",
    "last_err",
    "harvested",
    # ISSUE 15 serving-throughput signals (accrete-only, like the rest):
    # cumulative draft acceptance ratio and prefix-cache-paid prompt
    # tokens — the router's "is this replica's cache hot for this
    # traffic" inputs.  None for replicas predating them.
    "spec_accept_rate",
    "prefix_hit_tokens",
    # ISSUE 16 SLO burn signals: the replica's worst burn rate across
    # every (objective, window) series and its smallest remaining error
    # budget — the exact inputs ROADMAP item 5's admission shedding
    # reads.  None for replicas predating them (or with PTPU_SLO unset).
    "slo_max_burn_rate",
    "slo_min_budget_remaining",
    # ISSUE 18 circuit-breaker state: filled by Router.fleet_view()
    # (the breaker lives in the router process, not the aggregator —
    # the aggregator-side builder reports None for both), so dashboards
    # reading the router feed see WHY a replica takes no traffic.
    "breaker_state",
    "breaker_trips",
    # ISSUE 19 multi-tenant serving: per-tenant rollup parsed from the
    # replica's serving/tenant_* labeled series — {tenant: {"tokens",
    # "admitted", "shed"}}, empty dict when no tenant-labeled traffic
    # has hit the replica, None for replicas predating the key.
    "tenants",
    # ISSUE 20 memory microscope: KV-pool pressure signals for capacity-
    # aware routing — live blocks in use, pool utilization (0..1),
    # cumulative kv_pressure flight dumps written (a rising value means
    # the replica is thrashing), and {tenant: blocks_held} parsed from
    # the serving/kv_blocks_held labeled gauge.  None for replicas
    # predating them (or running with PTPU_MEMOBS off).
    "kv_blocks_in_use",
    "kv_block_utilization",
    "kv_pressure_dumps",
    "tenant_kv_blocks",
)

# -- wide-event request log (ISSUE 16) --------------------------------------
# One structured event per finished request (monitor/reqlog.py), served
# at GET /requests/recent and optionally sunk to rotating JSONL
# (PTPU_REQLOG).  Keys only ever accrete and schema_version only ever
# increases — consumers (the cache-aware router's stickiness debugging,
# log pipelines) key on both.  The canonical builder carries a
# ``# ptpu-wire: reqlog-event`` anchor and must emit EXACTLY these keys.
REQLOG_SCHEMA_VERSION = 2        # v2 (ISSUE 19): + tenant, priority

REQLOG_EVENT_KEYS = (
    "schema_version",
    "rid",
    "trace_id",
    "replica_id",
    "ts",
    "arrival_ts",
    "prompt_tokens",
    "generated_tokens",
    "queue_wait_s",
    "ttft_s",
    "tpot_avg_s",
    "tpot_max_s",
    "prefill_chunks",
    "prefix_hit_tokens",
    "spec_proposed",
    "spec_accepted",
    "preemptions",
    "peak_kv_blocks",
    # reason vocabulary (accrete-only, like the keys): stop | abort |
    # deadline | released | migrated | shed | rejected — "migrated"
    # (ISSUE 17) marks a request handed off to another replica (drain
    # requeue, failover resubmission, prefill→decode disaggregation),
    # NOT a failure; "shed" (ISSUE 19) marks best-effort work dropped by
    # SLO-aware admission control (HTTP 429) and "rejected" an HTTP-level
    # client error (auth/parse) that never reached the scheduler;
    # monitor/slo.py's error_rate counts all three good.
    "finish_reason",
    # ISSUE 19 multi-tenant serving: fair-share tenant (None = default
    # pool) and priority class (interactive | batch | best-effort).
    "tenant",
    "priority",
)

# -- multi-replica router protocol (ISSUE 17) --------------------------------
# The request frames the serving router and its replica workers exchange
# over distributed/rpc.py (which provides transport framing + the trace
# header; these are the PAYLOAD dict schemas).  One version number
# covers the protocol: it only ever increases, and every frame carries
# it so a replica can reject a future router instead of mis-parsing it.
# Keys accrete-only; canonical builders live in serving/router.py under
# the matching ``# ptpu-wire: router-*`` anchors.
ROUTER_SCHEMA_VERSION = 1

# router -> replica: admit one request
ROUTER_SUBMIT_KEYS = (
    "schema_version",
    "rid",              # the ROUTER's request id (replica ids are local)
    "prompt_ids",       # list[int]
    "params",           # SamplingParams as a plain dict (version-skew
    #                     safe: unknown fields are dropped, not fatal)
    "trace",            # monitor.trace inject() header, or None
)

# replica -> router: one finished (or failed) request
ROUTER_RESULT_KEYS = (
    "schema_version",
    "rid",
    "replica",          # reporting replica's name
    "ok",               # bool; False => error is set, token_ids is None
    "token_ids",        # [prompt + generated] ints, engine row shape
    "finish_reason",    # stop | abort | deadline | released | migrated |
    #                     shed | rejected (ISSUE 19 vocab accretions)
    "error",            # str | None
)

# prefill worker -> router -> decode worker: a mid-flight request with
# its KV shipped block-for-block via the bit-exact swap_out/swap_in path
ROUTER_HANDOFF_KEYS = (
    "schema_version",
    "rid",
    "prompt_ids",
    "output_ids",       # tokens emitted so far (>= 1: prefill samples
    #                     the first token from its final logits)
    "params",
    "key",              # the row's evolved PRNG key (uint32[2]) — what
    #                     keeps seeded sampling token-identical across
    #                     the migration
    "kv",               # BlockKVCache.swap_out() host snapshot
    "trace",
)

# replica -> router: one poll response (drained by Router.poll() each
# pump cycle — results, prefill handoffs, and drain-requeued submits
# ride ONE rpc round trip)
ROUTER_POLL_KEYS = (
    "schema_version",
    "replica",
    "draining",         # True once PreemptionHandler fired: admission
    #                     stopped, waiting requests come back requeued
    "results",          # list of ROUTER_RESULT_KEYS frames
    "handoffs",         # list of ROUTER_HANDOFF_KEYS frames
    "requeued",         # list of ROUTER_SUBMIT_KEYS frames
)

# the router's exported metric names (the fleet scrape surface a
# dashboard keys on — renaming one orphans its panels, so the set is
# declared wire like the feed keys)
ROUTER_METRIC_NAMES = (
    "router/requests",
    "router/dispatched",
    "router/sticky_hits",
    "router/deadline_rejected",
    "router/failovers",
    "router/requeued",
    "router/handoffs",
    "router/stale_results",
    "router/errors",
    "router/queue_depth",
    "router/inflight",
    # ISSUE 18 chaos hardening: breaker trips/open-count and the
    # router-side in-flight deadline finalizer
    "router/breaker_trips",
    "router/breaker_open",
    "router/deadline_inflight",
)

# -- HTTP API error body (ISSUE 19) ------------------------------------------
# The ``{"error": {...}}`` inner object every non-2xx response from
# serving/api.py carries — OpenAI-client-shaped, so off-the-shelf SDKs
# surface `message`/`type`/`code` without translation.  Accrete-only;
# the canonical builder in serving/api.py carries a
# ``# ptpu-wire: api-error`` anchor and must emit EXACTLY these keys.
API_ERROR_KEYS = (
    "message",          # human-readable description
    "type",             # invalid_request_error | authentication_error |
    #                     not_found_error | rate_limit_error | api_error
    "code",             # machine key: e.g. "shed" (SLO admission drop),
    #                     "deadline", "model_not_found", None
    "param",            # offending request field, or None
)
