"""Live observability endpoint — a stdlib ``http.server`` thread serving
the process's metrics and traces while it runs:

- ``GET /metrics``       — Prometheus text exposition (the PR-1 exporter),
  scrapeable by any Prometheus/agent (and by ``monitor.fleet``);
- ``GET /healthz``       — JSON liveness: pid, uptime, seconds since the
  last completed span/step (the watchdog's signal — a scraper can alert
  on stalls without attaching a debugger), plus identity (host, rank /
  replica_id when known) so a fleet rollup can label replicas without
  out-of-band config;
- ``GET /traces/<id>``   — one trace's finished spans as JSON (the ids
  come from ``LLMEngine.request_trace`` / ``trace.trace_ids()``);
- ``GET /flight/latest`` — the newest flight-recorder dump in
  ``PTPU_FLIGHT_DIR`` (404 when none) — how the fleet aggregator
  harvests a stalled replica's post-mortem while the main thread hangs
  (this endpoint runs on the daemon http thread);
- ``GET /requests/recent[?n=K]`` — the wide-event request-log ring
  (``monitor/reqlog.py``, ISSUE 16), newest first — one structured
  event per finished serving request;
- ``GET /slo``           — the SLO burn-rate report (``monitor/slo.py``):
  per-objective fast/slow-window burn rates and remaining error budget;
- ``GET /kv``            — the memory microscope's KV pool map
  (``monitor/memory.py``, ISSUE 20): block counts, fragmentation,
  refcount histogram, lifecycle-event ledger and ranked holders.  The
  handler reads the last snapshot the engine *published*, never live
  engine state — no engine lock from this daemon thread;
- ``GET /memory/timeline`` — the bounded HBM/host memory timeline ring
  (monotonic ts, hbm_peak, hbm_in_use, host_rss per reading);
- ``GET /profile?secs=N`` — on-demand device profiling (ISSUE 12): runs
  a ``jax.profiler`` trace capture for N seconds (default 1, clamped to
  120) and returns the dump directory as a zip (perfetto/tensorboard-
  loadable xplane protos).  Single-flight: a capture already in
  progress answers a loud 409; a backend without profiler support
  answers a clean 501 (warned once, never a crash).  Runs on the http
  daemon thread, so a fleet aggregator can pull a trace from a slow
  replica without restarting it.

Launch: ``monitor.start_server(port)`` (port 0 = ephemeral; the chosen
port is on the returned server), or ``EngineConfig(metrics_port=...)``.
When ``PTPU_FLEET_STORE=host:port`` names a TCPStore, ``start_server``
also self-registers the endpoint there so a ``fleet.FleetAggregator``
auto-discovers it (launch/elastic jobs get fleet scraping for free).
The server runs on a daemon thread and binds 127.0.0.1 by default —
exposing it wider is an explicit ``host=`` decision.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["MonitorServer", "start_server", "stop_server",
           "set_identity", "identity"]

# uptime is ELAPSED time: monotonic survives NTP steps/suspend, where a
# wall-clock delta could report negative or hours-wrong uptime
_started_at = time.monotonic()

# -- identity ---------------------------------------------------------------
# /healthz schema: version bumped whenever keys are added (never removed/
# renamed — the PR-5 endpoint consumers stay byte-compatible).  v3 adds
# the process-identity gauges (rss_bytes, open_fds) the fleet router's
# load-aware dispatch wants.  Declared in the ONE wire registry
# (monitor/wire.py) so version-skew drift is a lint failure (ISSUE 14).
from .wire import HEALTHZ_SCHEMA_VERSION as SCHEMA_VERSION  # noqa: E402

_identity_override = {}


def set_identity(replica_id=None, rank=None) -> None:
    """Pin this process's fleet identity explicitly (overrides the
    PTPU_REPLICA_ID / PADDLE_TRAINER_ID env defaults)."""
    if replica_id is not None:
        _identity_override["replica_id"] = str(replica_id)
    if rank is not None:
        _identity_override["rank"] = int(rank)


def identity() -> dict:
    """host + (when known) rank/replica_id — the fields a fleet rollup
    labels replicas with.  rank comes from the launcher's
    PADDLE_TRAINER_ID, replica_id from PTPU_REPLICA_ID (both overridable
    via :func:`set_identity`); absent fields are omitted, not null."""
    out = {"host": socket.gethostname(), "schema_version": SCHEMA_VERSION}
    rank = _identity_override.get("rank")
    if rank is None:
        env = os.environ.get("PADDLE_TRAINER_ID")
        rank = int(env) if env and env.isdigit() else None
    if rank is not None:
        out["rank"] = rank
    rid = _identity_override.get("replica_id") \
        or os.environ.get("PTPU_REPLICA_ID")
    if rid:
        out["replica_id"] = rid
    return out


def _rss_bytes():
    """Resident set size — /proc on linux, peak-RSS rusage fallback
    elsewhere; None when neither answers (fields are omitted, not
    null)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        # peak, not current — documented best-effort fallback.
        # ru_maxrss units differ per platform: KiB on linux, BYTES on
        # macOS — the one platform that always takes this branch
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except (ImportError, OSError, ValueError):
        return None


def _open_fds():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


# -- on-demand device profiling (ISSUE 12) ----------------------------------

class ProfilerUnavailable(RuntimeError):
    """This process cannot capture a device profile (no jax, or the
    backend's profiler refused) — the endpoint answers 501."""


# single-flight: jax.profiler supports ONE trace session per process;
# a second concurrent capture must 409, not corrupt the first
_profile_flight = threading.Lock()


def _capture_profile(secs: float) -> bytes:
    """Run a ``jax.profiler`` trace capture for `secs` seconds and
    return the dump directory zipped (xplane protos + any tool data —
    the artifact perfetto/tensorboard load).  Raises
    :class:`ProfilerUnavailable` where the profiler cannot run; the
    caller owns the single-flight lock."""
    import io
    import shutil
    import tempfile
    import zipfile

    try:
        import jax
    except Exception as e:   # headless monitor process: no jax at all
        raise ProfilerUnavailable(f"jax unavailable: {e!r}")
    d = tempfile.mkdtemp(prefix="ptpu_profile_")
    try:
        try:
            jax.profiler.start_trace(d)
        except Exception as e:
            raise ProfilerUnavailable(f"start_trace failed: {e!r}")
        try:
            time.sleep(secs)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:   # a torn session leaves no artifact
                raise ProfilerUnavailable(f"stop_trace failed: {e!r}")
        buf = io.BytesIO()
        n = 0
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _dirs, files in os.walk(d):
                for fn in sorted(files):
                    p = os.path.join(root, fn)
                    z.write(p, os.path.relpath(p, d))
                    n += 1
        if n == 0:
            raise ProfilerUnavailable("profiler produced no artifact")
        return buf.getvalue()
    finally:
        shutil.rmtree(d, ignore_errors=True)


class _Handler(BaseHTTPRequestHandler):
    server_version = "ptpu-monitor/2"

    def _send(self, code: int, body, ctype: str, extra_headers=()):
        data = body if isinstance(body, bytes) else body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _do_profile(self, query: str):
        import urllib.parse
        import warnings

        qs = urllib.parse.parse_qs(query)
        try:
            secs = float(qs.get("secs", ["1"])[0])
        except ValueError:
            self._send(400, json.dumps(
                {"error": "secs must be a number"}), "application/json")
            return
        secs = min(max(secs, 0.05), 120.0)
        if not _profile_flight.acquire(blocking=False):
            self._send(409, json.dumps(
                {"error": "profile capture already in flight"}),
                "application/json")
            return
        try:
            body = _capture_profile(secs)
        except ProfilerUnavailable as e:
            warnings.warn(f"/profile: device profiling unavailable: {e}")
            self._send(501, json.dumps(
                {"error": str(e)}), "application/json")
            return
        except Exception as e:   # capture blew up mid-way: truthfully 500
            self._send(500, json.dumps({"error": repr(e)}),
                       "application/json")
            return
        finally:
            _profile_flight.release()
        self._send(200, body, "application/zip", extra_headers=(
            ("Content-Disposition",
             f'attachment; filename="ptpu_profile_{os.getpid()}.zip"'),))

    def do_GET(self):   # noqa: N802 (http.server API)
        from . import enabled, export_prometheus, flight, trace

        raw = self.path
        query = raw.split("?", 1)[1] if "?" in raw else ""
        path = raw.split("?", 1)[0].rstrip("/") or "/"
        routes = getattr(self.server, "routes", None)
        if routes and path in routes:
            try:
                code, body, ctype = routes[path]()
            except Exception as e:   # a broken route must not kill the
                # scrape endpoint — report it as a 500 body instead
                code, body, ctype = 500, json.dumps(
                    {"error": repr(e)}), "application/json"
            self._send(code, body, ctype)
        elif path == "/metrics":
            reg = getattr(self.server, "registry", None)
            text = export_prometheus() if reg is None \
                else reg.export_prometheus()
            self._send(200, text,
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            doc = {
                "status": "ok",
                "pid": os.getpid(),
                "uptime_s": round(time.monotonic() - _started_at, 3),
                "last_activity_age_s": round(trace.last_activity_age(), 3),
                "monitor_enabled": enabled(),
                "trace_enabled": trace.enabled(),
            }
            # process-identity gauges (schema v3): what the fleet
            # router's load-aware dispatch reads alongside queue depth
            rss = _rss_bytes()
            if rss is not None:
                doc["rss_bytes"] = rss
            fds = _open_fds()
            if fds is not None:
                doc["open_fds"] = fds
            doc.update(identity())
            self._send(200, json.dumps(doc), "application/json")
        elif path == "/profile":
            self._do_profile(query)
        elif path == "/flight/latest":
            p = flight.latest_dump()
            if p is None:
                self._send(404, json.dumps(
                    {"error": "no flight dump (PTPU_FLIGHT_DIR unset or "
                              "empty)"}), "application/json")
            else:
                try:
                    with open(p) as f:
                        body = f.read()
                    self._send(200, body, "application/json")
                except OSError as e:   # raced a cleanup between listdir
                    # and open — a 404 is the truthful answer
                    self._send(404, json.dumps({"error": repr(e)}),
                               "application/json")
        elif path.startswith("/traces/"):
            tid = path[len("/traces/"):]
            spans = trace.get_trace(tid)
            if not spans:
                self._send(404, json.dumps(
                    {"error": f"unknown trace {tid!r}"}), "application/json")
            else:
                self._send(200, json.dumps(spans), "application/json")
        elif path == "/requests/recent":
            from . import reqlog

            n = None
            for part in query.split("&"):
                if part.startswith("n="):
                    try:
                        n = int(part[2:])
                    except ValueError:
                        pass
            self._send(200, json.dumps({
                "enabled": reqlog.enabled(),
                "schema_version": reqlog.REQLOG_SCHEMA_VERSION,
                "events": reqlog.recent(n),
            }), "application/json")
        elif path == "/slo":
            from . import slo

            self._send(200, json.dumps(slo.report()), "application/json")
        elif path == "/kv":
            # the memory microscope's pool map (ISSUE 20).  Reads the
            # last PUBLISHED snapshot slot only — this daemon thread
            # never touches the engine lock or walks live pool state
            from . import memory

            self._send(200, json.dumps(memory.kv_report()),
                       "application/json")
        elif path == "/memory/timeline":
            from . import memory

            self._send(200, json.dumps(memory.timeline_report()),
                       "application/json")
        elif path == "/":
            extra = " ".join(sorted(routes)) + " " if routes else ""
            self._send(200, "paddle_tpu monitor: /metrics /healthz "
                            "/traces/<id> /flight/latest "
                            "/requests/recent /slo /kv /memory/timeline "
                            f"/profile?secs=N {extra}\n",
                       "text/plain; charset=utf-8")
        else:
            self._send(404, "not found\n", "text/plain; charset=utf-8")

    def log_message(self, fmt, *args):
        pass   # scrapes every few seconds must not spam stderr


class MonitorServer:
    """A running endpoint; ``.port`` is the bound port (useful with
    port=0), ``.stop()`` shuts it down.

    ``registry``: an alternate StatRegistry whose exposition /metrics
    serves instead of the process default — the fleet aggregator swaps a
    freshly merged registry in per scrape cycle (assign
    ``server.registry``; reads are atomic under the GIL).
    ``routes``: extra exact-path GET handlers, each a zero-arg callable
    returning ``(status, body_str, content_type)`` — how
    ``/fleet/healthz`` rides the same server."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None, routes=None):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.registry = registry
        self._httpd.routes = dict(routes) if routes else None
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ptpu-monitor-http",
            daemon=True)
        self._thread.start()

    @property
    def registry(self):
        return self._httpd.registry

    @registry.setter
    def registry(self, reg):
        self._httpd.registry = reg

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __repr__(self):
        return f"MonitorServer({self.url})"


_server = None
_server_lock = threading.Lock()


def start_server(port: int = 0, host: str = "127.0.0.1") -> MonitorServer:
    """Start (or return) the process-wide endpoint.  Asking for a
    DIFFERENT explicit port while one is already bound warns instead of
    silently handing back the old server — a scrape target configured
    for the requested port would otherwise look down forever.

    With ``PTPU_FLEET_STORE=host:port`` set, a freshly started server
    self-registers its endpoint in that TCPStore (best-effort: a dead
    store warns, it never fails the process being monitored)."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MonitorServer(port, host)
            if os.environ.get("PTPU_FLEET_STORE"):
                from . import fleet

                try:
                    fleet.register_replica(_server)
                except Exception as e:
                    # registration is advisory — the replica still serves
                    # locally; an unreachable store must not take down
                    # the process that merely wanted metrics
                    import warnings

                    warnings.warn(
                        f"monitor.start_server: fleet registration at "
                        f"PTPU_FLEET_STORE="
                        f"{os.environ['PTPU_FLEET_STORE']!r} failed: "
                        f"{e!r}")
        elif port not in (0, _server.port):
            import warnings

            warnings.warn(
                f"monitor.start_server({port}): endpoint already bound "
                f"on port {_server.port}; returning the existing server "
                "— stop_server() first to rebind")
        return _server


def stop_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
