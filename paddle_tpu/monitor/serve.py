"""Live observability endpoint — a stdlib ``http.server`` thread serving
the process's metrics and traces while it runs:

- ``GET /metrics``       — Prometheus text exposition (the PR-1 exporter),
  scrapeable by any Prometheus/agent (and by ``monitor.fleet``);
- ``GET /healthz``       — JSON liveness: pid, uptime, seconds since the
  last completed span/step (the watchdog's signal — a scraper can alert
  on stalls without attaching a debugger), plus identity (host, rank /
  replica_id when known) so a fleet rollup can label replicas without
  out-of-band config;
- ``GET /traces/<id>``   — one trace's finished spans as JSON (the ids
  come from ``LLMEngine.request_trace`` / ``trace.trace_ids()``);
- ``GET /flight/latest`` — the newest flight-recorder dump in
  ``PTPU_FLIGHT_DIR`` (404 when none) — how the fleet aggregator
  harvests a stalled replica's post-mortem while the main thread hangs
  (this endpoint runs on the daemon http thread).

Launch: ``monitor.start_server(port)`` (port 0 = ephemeral; the chosen
port is on the returned server), or ``EngineConfig(metrics_port=...)``.
When ``PTPU_FLEET_STORE=host:port`` names a TCPStore, ``start_server``
also self-registers the endpoint there so a ``fleet.FleetAggregator``
auto-discovers it (launch/elastic jobs get fleet scraping for free).
The server runs on a daemon thread and binds 127.0.0.1 by default —
exposing it wider is an explicit ``host=`` decision.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["MonitorServer", "start_server", "stop_server",
           "set_identity", "identity"]

# uptime is ELAPSED time: monotonic survives NTP steps/suspend, where a
# wall-clock delta could report negative or hours-wrong uptime
_started_at = time.monotonic()

# -- identity ---------------------------------------------------------------
# /healthz schema: version bumped whenever keys are added (never removed/
# renamed — the PR-5 endpoint consumers stay byte-compatible)
SCHEMA_VERSION = 2

_identity_override = {}


def set_identity(replica_id=None, rank=None) -> None:
    """Pin this process's fleet identity explicitly (overrides the
    PTPU_REPLICA_ID / PADDLE_TRAINER_ID env defaults)."""
    if replica_id is not None:
        _identity_override["replica_id"] = str(replica_id)
    if rank is not None:
        _identity_override["rank"] = int(rank)


def identity() -> dict:
    """host + (when known) rank/replica_id — the fields a fleet rollup
    labels replicas with.  rank comes from the launcher's
    PADDLE_TRAINER_ID, replica_id from PTPU_REPLICA_ID (both overridable
    via :func:`set_identity`); absent fields are omitted, not null."""
    out = {"host": socket.gethostname(), "schema_version": SCHEMA_VERSION}
    rank = _identity_override.get("rank")
    if rank is None:
        env = os.environ.get("PADDLE_TRAINER_ID")
        rank = int(env) if env and env.isdigit() else None
    if rank is not None:
        out["rank"] = rank
    rid = _identity_override.get("replica_id") \
        or os.environ.get("PTPU_REPLICA_ID")
    if rid:
        out["replica_id"] = rid
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "ptpu-monitor/2"

    def _send(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):   # noqa: N802 (http.server API)
        from . import enabled, export_prometheus, flight, trace

        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        routes = getattr(self.server, "routes", None)
        if routes and path in routes:
            try:
                code, body, ctype = routes[path]()
            except Exception as e:   # a broken route must not kill the
                # scrape endpoint — report it as a 500 body instead
                code, body, ctype = 500, json.dumps(
                    {"error": repr(e)}), "application/json"
            self._send(code, body, ctype)
        elif path == "/metrics":
            reg = getattr(self.server, "registry", None)
            text = export_prometheus() if reg is None \
                else reg.export_prometheus()
            self._send(200, text,
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            doc = {
                "status": "ok",
                "pid": os.getpid(),
                "uptime_s": round(time.monotonic() - _started_at, 3),
                "last_activity_age_s": round(trace.last_activity_age(), 3),
                "monitor_enabled": enabled(),
                "trace_enabled": trace.enabled(),
            }
            doc.update(identity())
            self._send(200, json.dumps(doc), "application/json")
        elif path == "/flight/latest":
            p = flight.latest_dump()
            if p is None:
                self._send(404, json.dumps(
                    {"error": "no flight dump (PTPU_FLIGHT_DIR unset or "
                              "empty)"}), "application/json")
            else:
                try:
                    with open(p) as f:
                        body = f.read()
                    self._send(200, body, "application/json")
                except OSError as e:   # raced a cleanup between listdir
                    # and open — a 404 is the truthful answer
                    self._send(404, json.dumps({"error": repr(e)}),
                               "application/json")
        elif path.startswith("/traces/"):
            tid = path[len("/traces/"):]
            spans = trace.get_trace(tid)
            if not spans:
                self._send(404, json.dumps(
                    {"error": f"unknown trace {tid!r}"}), "application/json")
            else:
                self._send(200, json.dumps(spans), "application/json")
        elif path == "/":
            extra = " ".join(sorted(routes)) + " " if routes else ""
            self._send(200, "paddle_tpu monitor: /metrics /healthz "
                            f"/traces/<id> /flight/latest {extra}\n",
                       "text/plain; charset=utf-8")
        else:
            self._send(404, "not found\n", "text/plain; charset=utf-8")

    def log_message(self, fmt, *args):
        pass   # scrapes every few seconds must not spam stderr


class MonitorServer:
    """A running endpoint; ``.port`` is the bound port (useful with
    port=0), ``.stop()`` shuts it down.

    ``registry``: an alternate StatRegistry whose exposition /metrics
    serves instead of the process default — the fleet aggregator swaps a
    freshly merged registry in per scrape cycle (assign
    ``server.registry``; reads are atomic under the GIL).
    ``routes``: extra exact-path GET handlers, each a zero-arg callable
    returning ``(status, body_str, content_type)`` — how
    ``/fleet/healthz`` rides the same server."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None, routes=None):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.registry = registry
        self._httpd.routes = dict(routes) if routes else None
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ptpu-monitor-http",
            daemon=True)
        self._thread.start()

    @property
    def registry(self):
        return self._httpd.registry

    @registry.setter
    def registry(self, reg):
        self._httpd.registry = reg

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __repr__(self):
        return f"MonitorServer({self.url})"


_server = None
_server_lock = threading.Lock()


def start_server(port: int = 0, host: str = "127.0.0.1") -> MonitorServer:
    """Start (or return) the process-wide endpoint.  Asking for a
    DIFFERENT explicit port while one is already bound warns instead of
    silently handing back the old server — a scrape target configured
    for the requested port would otherwise look down forever.

    With ``PTPU_FLEET_STORE=host:port`` set, a freshly started server
    self-registers its endpoint in that TCPStore (best-effort: a dead
    store warns, it never fails the process being monitored)."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MonitorServer(port, host)
            if os.environ.get("PTPU_FLEET_STORE"):
                from . import fleet

                try:
                    fleet.register_replica(_server)
                except Exception as e:
                    # registration is advisory — the replica still serves
                    # locally; an unreachable store must not take down
                    # the process that merely wanted metrics
                    import warnings

                    warnings.warn(
                        f"monitor.start_server: fleet registration at "
                        f"PTPU_FLEET_STORE="
                        f"{os.environ['PTPU_FLEET_STORE']!r} failed: "
                        f"{e!r}")
        elif port not in (0, _server.port):
            import warnings

            warnings.warn(
                f"monitor.start_server({port}): endpoint already bound "
                f"on port {_server.port}; returning the existing server "
                "— stop_server() first to rebind")
        return _server


def stop_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
