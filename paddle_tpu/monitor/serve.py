"""Live observability endpoint — a stdlib ``http.server`` thread serving
the process's metrics and traces while it runs:

- ``GET /metrics``      — Prometheus text exposition (the PR-1 exporter),
  scrapeable by any Prometheus/agent;
- ``GET /healthz``      — JSON liveness: pid, uptime, seconds since the
  last completed span/step (the watchdog's signal — a scraper can alert
  on stalls without attaching a debugger);
- ``GET /traces/<id>``  — one trace's finished spans as JSON (the ids
  come from ``LLMEngine.request_trace`` / ``trace.trace_ids()``).

Launch: ``monitor.start_server(port)`` (port 0 = ephemeral; the chosen
port is on the returned server), or ``EngineConfig(metrics_port=...)``.
The server runs on a daemon thread and binds 127.0.0.1 by default —
exposing it wider is an explicit ``host=`` decision.
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["MonitorServer", "start_server", "stop_server"]

# uptime is ELAPSED time: monotonic survives NTP steps/suspend, where a
# wall-clock delta could report negative or hours-wrong uptime
_started_at = time.monotonic()


class _Handler(BaseHTTPRequestHandler):
    server_version = "ptpu-monitor/2"

    def _send(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):   # noqa: N802 (http.server API)
        from . import enabled, export_prometheus, trace

        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._send(200, export_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            self._send(200, json.dumps({
                "status": "ok",
                "pid": os.getpid(),
                "uptime_s": round(time.monotonic() - _started_at, 3),
                "last_activity_age_s": round(trace.last_activity_age(), 3),
                "monitor_enabled": enabled(),
                "trace_enabled": trace.enabled(),
            }), "application/json")
        elif path.startswith("/traces/"):
            tid = path[len("/traces/"):]
            spans = trace.get_trace(tid)
            if not spans:
                self._send(404, json.dumps(
                    {"error": f"unknown trace {tid!r}"}), "application/json")
            else:
                self._send(200, json.dumps(spans), "application/json")
        elif path == "/":
            self._send(200, "paddle_tpu monitor: /metrics /healthz "
                            "/traces/<id>\n", "text/plain; charset=utf-8")
        else:
            self._send(404, "not found\n", "text/plain; charset=utf-8")

    def log_message(self, fmt, *args):
        pass   # scrapes every few seconds must not spam stderr


class MonitorServer:
    """A running endpoint; ``.port`` is the bound port (useful with
    port=0), ``.stop()`` shuts it down."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ptpu-monitor-http",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __repr__(self):
        return f"MonitorServer({self.url})"


_server = None
_server_lock = threading.Lock()


def start_server(port: int = 0, host: str = "127.0.0.1") -> MonitorServer:
    """Start (or return) the process-wide endpoint.  Asking for a
    DIFFERENT explicit port while one is already bound warns instead of
    silently handing back the old server — a scrape target configured
    for the requested port would otherwise look down forever."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MonitorServer(port, host)
        elif port not in (0, _server.port):
            import warnings

            warnings.warn(
                f"monitor.start_server({port}): endpoint already bound "
                f"on port {_server.port}; returning the existing server "
                "— stop_server() first to rebind")
        return _server


def stop_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
