"""HLO-level kernel attribution — the *inside a compiled program* half of
perf attribution (ISSUE 12's "program microscope").

``monitor.perf`` (PR 6) attributes wall time to whole compiled programs:
MFU, roofline bound, achieved-vs-optimal.  The next perf arc — the
mega-kernelized decode layer (ROADMAP item 4) — needs to see *inside*
those programs: which fusions XLA actually emitted, what each must read
and compute, and therefore which fusion is the next rewrite target.
This module parses the optimized HLO text (``compiled.as_text()``,
captured on the same one-per-signature AOT path the perf hook already
pays) into a per-instruction table with flops/bytes estimated from the
shape algebra, and ranks the entry computation's instructions — the
units XLA dispatches as kernels/thunks — by their roofline-model time.

Estimation model (attribution, not accounting):

- **flops** from opcode + shapes: ``dot`` = 2·|out|·K (K = product of
  the lhs contracting dims), elementwise = |out|, ``reduce`` = |inputs|,
  ``convolution`` = 2·|out|·(kernel elements / output features),
  ``fusion``/``call`` = the called computation's total.  ``while``/
  ``conditional`` bodies have unknowable static trip counts and count 0
  (flagged via ``estimated=False`` rows); ``custom-call`` likewise.
- **bytes** = operand bytes + result bytes at the instruction boundary.
  For a fusion that is exactly its HBM traffic (internals stay in
  registers/VMEM) — the number the roofline wants.

Dialect tolerance: jax 0.4.x prints ``%name = f32[8]{1,0} op(f32[8]
%operand)``; newer jax/XLA drop the ``%`` sigils and the inline operand
types.  The parser resolves operand shapes through a per-computation
symbol table instead of trusting inline types, so both dialects (and
mixtures) parse to the same numbers — pinned by golden-text fixtures in
tests/test_hlo.py.  Anything unparseable degrades to 'unavailable'
(``HloParseError`` at parse level, an unavailable record at capture
level) — never garbage numbers, the PR-6 degradation contract.

Gate/import contract (shared with the rest of monitor): stdlib-only,
never imports jax; text arrives from callers that already hold the
compiled object, and capture happens only on the PTPU_PERF AOT path.

Exported metrics: ``perf/hlo_ops{fn}`` (entry instructions dispatched),
``perf/fusions{fn}`` (fusion instructions in the entry computation).
"""
from __future__ import annotations

import os
import re
import threading

__all__ = [
    "HloParseError", "HloInstr", "HloComputation", "HloProgram",
    "parse_hlo", "analyze", "capture", "get", "labels", "report",
    "reset",
]


UNAVAILABLE = "unavailable"


class HloParseError(ValueError):
    """The text is not HLO this parser understands (new dialect, MLIR
    bytecode, garbage).  Callers degrade to 'unavailable'."""


# -- shapes -----------------------------------------------------------------

# bytes per element; sub-byte types keep fractional sizes (totals round)
_DTYPE_BYTES = {
    "pred": 1, "s2": 0.25, "u2": 0.25, "s4": 0.5, "u4": 0.5,
    "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,\s]*)\](?:\{[^}]*\})?")


def _dtype_bytes(dtype: str) -> float:
    if dtype in _DTYPE_BYTES:
        return _DTYPE_BYTES[dtype]
    if dtype.startswith("f8"):      # f8e4m3fn / f8e5m2 / ...
        return 1.0
    return 0.0                      # unknown layout-only type


class _Shape:
    __slots__ = ("elems", "bytes")

    def __init__(self, elems: float, nbytes: float):
        self.elems = elems
        self.bytes = nbytes


def _parse_shape(text: str) -> "_Shape | None":
    """One shape (`f32[8,16]{1,0}`) or a tuple of them; None when `text`
    contains no shape syntax at all."""
    total_e = total_b = 0.0
    seen = False
    for m in _SHAPE_RE.finditer(text):
        seen = True
        dims = [int(d) for d in m.group(2).replace(" ", "").split(",")
                if d]
        elems = 1.0
        for d in dims:
            elems *= d
        total_e += elems
        total_b += elems * _dtype_bytes(m.group(1))
    return _Shape(total_e, total_b) if seen else None


def _dims_of(text: str) -> tuple:
    m = _SHAPE_RE.search(text)
    if m is None:
        return ()
    return tuple(int(d) for d in m.group(2).replace(" ", "").split(",")
                 if d)


# -- instruction / computation model ----------------------------------------

class HloInstr:
    __slots__ = ("name", "opcode", "shape_text", "shape", "operands",
                 "attrs", "op_name", "calls", "is_root")

    def __init__(self, name, opcode, shape_text, operands, attrs,
                 is_root):
        self.name = name
        self.opcode = opcode
        self.shape_text = shape_text
        self.shape = _parse_shape(shape_text) or _Shape(0.0, 0.0)
        self.operands = operands          # resolved operand NAMES
        self.attrs = attrs
        self.is_root = is_root
        m = re.search(r'op_name="([^"]*)"', attrs)
        self.op_name = m.group(1) if m else None
        m = re.search(r"(?:calls|to_apply)=%?([\w.\-]+)", attrs)
        self.calls = m.group(1) if m else None


class HloComputation:
    __slots__ = ("name", "instrs", "is_entry", "symtab")

    def __init__(self, name, is_entry):
        self.name = name
        self.is_entry = is_entry
        self.instrs: list = []
        self.symtab: dict = {}            # instr name -> HloInstr

    def add(self, instr: HloInstr):
        self.instrs.append(instr)
        self.symtab[instr.name] = instr


class HloProgram:
    __slots__ = ("module", "computations", "entry")

    def __init__(self, module):
        self.module = module
        self.computations: dict = {}      # name -> HloComputation
        self.entry: "HloComputation | None" = None


# one line: `[ROOT ]%name = <shape> opcode(<operands>)[, attrs]`
_INSTR_HEAD_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-/]+)\s*=\s*(.*)$")
# computation header: `[ENTRY ]%name [(params)] [-> shape] {`
_COMP_RE = re.compile(
    r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*(\(.*\))?\s*(->\s*[^{]*)?\{\s*$")
_OPCODE_RE = re.compile(r"([\w\-]+)")


def _scan_call(text: str):
    """Split `opcode(operands)attrs` with paren-depth matching (operand
    types may themselves contain tuple parens)."""
    m = _OPCODE_RE.match(text)
    if m is None:
        return None
    opcode = m.group(1)
    rest = text[m.end():].lstrip()
    if not rest.startswith("("):
        return opcode, "", rest
    depth = 0
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return opcode, rest[1:i], rest[i + 1:]
    raise HloParseError(f"unbalanced operand parens in {text[:60]!r}")


def _operand_names(operands: str) -> list:
    """Trailing identifier of each top-level comma segment — works for
    `f32[8]{0} %x` (0.4.x) and bare `x` (newer) alike."""
    out, depth, seg = [], 0, []
    for ch in operands + ",":
        if ch == "," and depth == 0:
            s = "".join(seg).strip()
            if s:
                m = re.search(r"%?([\w.\-/]+)\s*$", s)
                if m:
                    out.append(m.group(1))
            seg = []
            continue
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        seg.append(ch)
    return out


def parse_hlo(text: str) -> HloProgram:
    """Parse optimized HLO text into an :class:`HloProgram`.  Raises
    :class:`HloParseError` when the text has no recognizable module/
    entry structure; individual odd lines inside a recognized module are
    skipped (forward compatibility beats completeness here)."""
    if not isinstance(text, str) or "HloModule" not in text:
        raise HloParseError("no HloModule header")
    prog = None
    current = None
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line.strip() or line.lstrip().startswith("//"):
            continue
        if line.lstrip().startswith("HloModule"):
            parts = line.split()
            prog = HloProgram(parts[1].rstrip(",") if len(parts) > 1
                              else "<unnamed>")
            continue
        if prog is None:
            continue
        stripped = line.strip()
        if stripped == "}":
            current = None
            continue
        if current is None:
            cm = _COMP_RE.match(line)
            if cm and "=" not in line.split("(", 1)[0]:
                current = HloComputation(cm.group(2),
                                         bool(cm.group(1)))
                prog.computations[current.name] = current
                if current.is_entry:
                    prog.entry = current
            continue
        im = _INSTR_HEAD_RE.match(line)
        if im is None:
            continue
        rhs = im.group(3)
        # result shape: a tuple `( ... )` or a plain shape prefix
        if rhs.startswith("("):
            depth, end = 0, None
            for i, ch in enumerate(rhs):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        end = i + 1
                        break
            if end is None:
                continue
            shape_text, call_text = rhs[:end], rhs[end:].lstrip()
        else:
            sm = _SHAPE_RE.match(rhs)
            if sm is None:
                continue               # tolerated odd line
            shape_text, call_text = sm.group(0), rhs[sm.end():].lstrip()
        scanned = _scan_call(call_text)
        if scanned is None:
            continue
        opcode, operands, attrs = scanned
        current.add(HloInstr(im.group(2), opcode, shape_text,
                             _operand_names(operands), attrs,
                             bool(im.group(1))))
    if prog is None or prog.entry is None or not prog.entry.instrs:
        raise HloParseError("no ENTRY computation found")
    return prog


# -- flops / bytes algebra --------------------------------------------------

_ZERO_FLOP = frozenset((
    "parameter", "constant", "copy", "copy-start", "copy-done",
    "reshape", "bitcast", "bitcast-convert", "transpose", "broadcast",
    "slice", "dynamic-slice", "dynamic-update-slice", "concatenate",
    "gather", "pad", "tuple", "get-tuple-element", "iota", "convert",
    "reverse", "after-all", "partition-id", "replica-id", "rng",
    "rng-bit-generator", "domain", "optimization-barrier",
))
_NO_BYTES = frozenset(("parameter", "constant", "tuple",
                       "get-tuple-element", "bitcast", "after-all"))
_UNKNOWN_COST = frozenset(("custom-call", "while", "conditional",
                           "infeed", "outfeed", "send", "recv",
                           "all-reduce", "all-gather", "reduce-scatter",
                           "all-to-all", "collective-permute", "fft",
                           "cholesky", "triangular-solve", "sort"))


def _operand_shape(instr, comp, i) -> "_Shape | None":
    if i >= len(instr.operands):
        return None
    dep = comp.symtab.get(instr.operands[i])
    # 0.4.x inline types are a fallback when the name is out of scope
    if dep is not None:
        return dep.shape
    return None


def _contracting_elems(instr, comp) -> float:
    m = re.search(r"lhs_contracting_dims={([0-9,\s]*)}", instr.attrs)
    lhs = comp.symtab.get(instr.operands[0]) if instr.operands else None
    if m is None or lhs is None:
        return 0.0
    dims = _dims_of(lhs.shape_text)
    k = 1.0
    for idx in (int(d) for d in m.group(1).replace(" ", "").split(",")
                if d):
        if idx < len(dims):
            k *= dims[idx]
    return k


def _instr_flops(instr, comp, prog, comp_flops,
                 _stack=None) -> "tuple[float, bool]":
    """(flops, estimated) — estimated=False marks opcodes whose static
    cost is unknowable (while bodies, custom calls): their 0 is a floor,
    not a claim."""
    op = instr.opcode
    if op in _ZERO_FLOP:
        return 0.0, True
    if op in _UNKNOWN_COST:
        return 0.0, False
    if op in ("fusion", "call"):
        if instr.calls and instr.calls in prog.computations:
            return _computation_flops(prog, instr.calls, comp_flops,
                                      _stack)
        return 0.0, False
    if op == "dot":
        k = _contracting_elems(instr, comp)
        if k <= 0:
            return 2.0 * instr.shape.elems, False
        return 2.0 * instr.shape.elems * k, True
    if op == "convolution":
        kern = _operand_shape(instr, comp, 1)
        out_dims = _dims_of(instr.shape_text)
        if kern is None or not out_dims:
            return 0.0, False
        # io convention: last kernel dim is output features — an
        # estimate; dim_labels parsing is not worth its fragility here
        feats = max(out_dims[-1], 1)
        return 2.0 * instr.shape.elems * kern.elems / feats, True
    if op.startswith("reduce"):
        total = 0.0
        n_in = max(1, len(instr.operands) // 2)   # inputs then inits
        for i in range(n_in):
            s = _operand_shape(instr, comp, i)
            total += s.elems if s else 0.0
        return (total, True) if total else (instr.shape.elems, True)
    if op == "scatter":
        upd = _operand_shape(instr, comp, 2)
        return (upd.elems if upd else instr.shape.elems), True
    # everything else: elementwise-ish, one flop per output element
    return instr.shape.elems, True


def _computation_flops(prog, name, memo, _stack=None):
    if name in memo:
        return memo[name]
    if _stack is None:
        _stack = set()
    if name in _stack:      # defensive: a cyclic call graph (malformed
        # text) must bail out, not blow the recursion limit — _stack
        # threads through _instr_flops so nested calls share it
        return 0.0, False
    _stack.add(name)
    comp = prog.computations[name]
    total, est = 0.0, True
    for instr in comp.instrs:
        f, e = _instr_flops(instr, comp, prog, memo, _stack)
        total += f
        est = est and e
    _stack.discard(name)
    memo[name] = (total, est)
    return total, est


def _instr_bytes(instr, comp) -> float:
    """Boundary traffic: operands + result.  Parameters/constants cost
    nothing themselves — their bytes are charged to their consumers."""
    if instr.opcode in _NO_BYTES:
        return 0.0
    total = instr.shape.bytes
    for i in range(len(instr.operands)):
        s = _operand_shape(instr, comp, i)
        if s is not None:
            total += s.bytes
    return total


# -- per-program analysis ---------------------------------------------------

_SKIP_IN_OPS = frozenset(("parameter", "constant", "get-tuple-element",
                          "tuple"))


def analyze(text: str) -> dict:
    """Parse + cost the entry computation.  Returns::

        {"available": True, "module": ..., "ops": N, "fusions": N,
         "computations": N, "flops": total, "bytes": total,
         "table": [{"name", "opcode", "flops", "bytes", "estimated",
                    "op_name"}, ...]}   # every entry instr, unranked

    Raises :class:`HloParseError` for unparseable text — ``capture``
    turns that into an unavailable record."""
    prog = parse_hlo(text)
    comp_flops: dict = {}
    table = []
    tot_f = tot_b = 0.0
    fusions = 0
    for instr in prog.entry.instrs:
        if instr.opcode in _SKIP_IN_OPS:
            continue
        f, est = _instr_flops(instr, prog.entry, prog, comp_flops)
        b = _instr_bytes(instr, prog.entry)
        tot_f += f
        tot_b += b
        if instr.opcode == "fusion":
            fusions += 1
        table.append({
            "name": instr.name,
            "opcode": instr.opcode,
            "flops": f,
            "bytes": b,
            "estimated": est,
            "op_name": instr.op_name,
        })
    return {
        "available": True,
        "module": prog.module,
        "ops": len(table),
        "fusions": fusions,
        "computations": len(prog.computations),
        "flops": tot_f,
        "bytes": tot_b,
        "table": table,
    }


# -- capture / store --------------------------------------------------------

def _registry():
    from . import get_registry

    return get_registry()


_store: dict = {}
_store_lock = threading.Lock()


def _max_bytes() -> int:
    try:
        return int(os.environ.get("PTPU_HLO_MAX_BYTES",
                                  str(16 * 2**20)))
    except ValueError:
        return 16 * 2**20


def capture(label: str, text) -> dict:
    """Analyze `text` for `label` and export the per-program gauges.
    NEVER raises: unparseable/oversized text stores an unavailable
    record (the PR-6 degradation contract) and counts a capture error.
    Called from ``perf.capture`` on the one-per-signature AOT path."""
    m = _registry()
    if isinstance(text, str) and len(text) > _max_bytes():
        result = {"available": False,
                  "error": f"hlo text {len(text)} bytes > "
                           f"PTPU_HLO_MAX_BYTES"}
    else:
        try:
            result = analyze(text)
        except Exception as e:   # HloParseError is the typed path, but
            # the contract is NEVER raising: an unforeseen dialect that
            # trips the parser some other way must degrade identically
            # (perf.capture sits on the hot AOT path — a parser bug must
            # not make a previously-working compile uncallable)
            result = {"available": False,
                      "error": f"{type(e).__name__}: {e}"}
            m.counter("perf/capture_errors",
                      "failed analysis/probe captures").labels(
                site="hlo_parse").inc()
    with _store_lock:
        _store[label] = result
    if result["available"]:
        m.gauge("perf/hlo_ops",
                "instructions in the entry computation (dispatched "
                "kernels/thunks)").labels(fn=label).set(result["ops"])
        m.gauge("perf/fusions",
                "fusion instructions in the entry computation").labels(
            fn=label).set(result["fusions"])
    return result


def get(label: str) -> "dict | None":
    with _store_lock:
        return _store.get(label)


def labels() -> list:
    with _store_lock:
        return sorted(_store)


def reset():
    with _store_lock:
        _store.clear()


# -- report -----------------------------------------------------------------

def _fmt_count(v) -> str:
    for cut, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if v >= cut:
            return f"{v / cut:.2f}{suf}"
    return f"{v:.0f}"


def report(label: str, top: int = 10) -> str:
    """Ranked per-instruction table for one captured program: entry
    instructions by roofline-model time (max of compute and bandwidth
    bounds on the current chip_spec), top-k shown.  '' when the label
    was never captured; an 'unavailable' line when its text did not
    parse."""
    rec = get(label)
    if rec is None:
        return ""
    if not rec.get("available"):
        return (f"hlo[{label}]: {UNAVAILABLE} "
                f"({rec.get('error', 'no analysis')})")
    from . import perf as _perf

    chip = _perf.chip_spec()

    def cost_s(row):
        return max(row["flops"] / chip.peak_flops,
                   row["bytes"] / chip.hbm_bw)

    rows = sorted(rec["table"], key=lambda r: -cost_s(r))
    total_s = sum(cost_s(r) for r in rows) or 1.0
    lines = [
        f"hlo[{label}] module={rec['module']}: {rec['ops']} ops, "
        f"{rec['fusions']} fusions, {rec['computations']} computations, "
        f"{_fmt_count(rec['flops'])}F {_fmt_count(rec['bytes'])}B",
        f"  {'instruction':32s} {'opcode':20s} {'flops':>8s} "
        f"{'bytes':>8s} {'est_us':>8s} {'share':>6s}",
    ]
    for r in rows[:top]:
        t = cost_s(r)
        name = r["name"][:32]
        mark = "" if r["estimated"] else "?"
        lines.append(
            f"  {name:32s} {r['opcode'][:20]:20s} "
            f"{_fmt_count(r['flops']):>8s} {_fmt_count(r['bytes']):>8s} "
            f"{t * 1e6:8.2f} {t / total_s * 100:5.1f}%{mark}")
        if r["op_name"]:
            lines.append(f"      {r['op_name'][:72]}")
    if len(rows) > top:
        lines.append(f"  ... {len(rows) - top} more instructions")
    return "\n".join(lines)
