"""Structured span tracing — the *where did the time go* half of the
monitor subsystem (the PR-1 StatRegistry is the *how much/how many* half).

A span is one timed operation with identity: ``trace_id`` groups every
span of one logical unit of work (a serving request, a guarded train
step), ``span_id`` names the span, ``parent_id`` links it under its
parent so a trace renders as a tree.  Producers:

- ``with trace.span("serving/prefill", chunk_len=64):`` — context-manager
  spans nest through a thread-local, so a span opened inside another
  becomes its child automatically;
- ``trace.start_span(name, parent=...)`` / ``Span.end()`` — manual spans
  for operations that start and finish in different call frames (a
  serving request lives across many engine steps);
- ``trace.attach(span)`` — re-parent the thread-local context onto an
  existing span from ANOTHER thread (DataLoader workers, async
  checkpoint writers), so cross-thread work lands in the right trace;
- ``trace.inject()`` / ``trace.extract(header)`` — serialize the current
  span's (trace_id, span_id) into a traceparent-style header and parse
  it back into a :class:`SpanContext` in ANOTHER process, so an rpc-
  issued request opens a *child* span on the remote worker and
  ``export_chrome_trace()`` shows one trace_id spanning processes
  (``distributed/rpc.py`` carries the header on every call).

Design constraints (shared with the metrics layer):

- **near-zero cost when disabled**: ``span()`` is one module-global read
  and returns a no-op singleton; guarded by the same <1 µs test that
  protects the PTPU_MONITOR gate (tests/test_trace.py).  Gate:
  ``PTPU_TRACE=1`` (default OFF — tracing allocates per event, metrics
  don't).
- **stdlib-only, no jax**: importable headlessly; chrome-trace export
  merges spans from `paddle_tpu.profiler`'s host tracer only when that
  module is ALREADY loaded (``sys.modules`` probe — never triggers an
  accelerator import from here).
- **bounded memory**: finished spans land in (a) the flight-recorder
  ring (`monitor.flight`) and (b) a per-trace store capped at
  ``PTPU_TRACE_MAX_TRACES`` traces (oldest evicted), which backs
  ``LLMEngine.request_trace(rid)`` and the ``/traces/<id>`` endpoint.
- **tail-based sampling** (ISSUE 16, opt-in via ``PTPU_TRACE_TAIL=<n>``):
  the keep decision is deferred to ROOT-span end, when the whole trace
  is known.  Interesting traces — any span errored, the root finished
  abnormally (``finish`` attr other than ``"stop"``: abort/deadline/
  released), or a producer stamped ``keep=True`` (the engine does for
  SLO-violating requests) — are ALWAYS kept; boring fast-path traces
  are kept only while the per-60s-window budget of ``n`` lasts, then
  dropped from the store.  The flight ring still sees every span
  (crash forensics wants the recent past, sampled or not).  Unset =
  today's keep-everything behaviour; ``0`` = keep only interesting.

Timestamps use ``time.perf_counter_ns`` — the same clock as the
profiler's ``RecordEvent`` spans — so ``export_chrome_trace()`` puts
framework spans and RecordEvent spans on ONE Perfetto timeline.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from collections import OrderedDict

__all__ = [
    "Span", "SpanContext", "span", "start_span", "current_span", "attach",
    "inject", "extract", "get_trace",
    "trace_ids", "chrome_events", "export_chrome_trace", "enabled",
    "enable", "refresh", "reset", "heartbeat", "last_activity_age",
    "tail_budget", "set_tail_budget",
]


def _env_enabled() -> bool:
    return os.environ.get("PTPU_TRACE", "0").strip().lower() not in (
        "0", "false", "off", "")


_enabled = _env_enabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True):
    """Flip span collection on/off at runtime (overrides PTPU_TRACE)."""
    global _enabled
    _enabled = bool(on)


def refresh():
    """Re-read PTPU_TRACE (+ PTPU_TRACE_TAIL) from the environment."""
    global _enabled, _tail_budget
    _enabled = _env_enabled()
    _tail_budget = _env_tail()


# -- identity ---------------------------------------------------------------
# ids are "<run>-<n>": unique within the process and cheap to mint (one
# itertools.count() next, no urandom per span); the run prefix keeps ids
# from colliding across processes in one flight dir.
_RUN = f"{os.getpid():x}{time.time_ns() & 0xFFFFFF:06x}"
_ids = itertools.count(1)


def _next_id(prefix: str = "s") -> str:
    return f"{prefix}{_RUN}-{next(_ids):x}"


# -- liveness (the watchdog's signal) ---------------------------------------
_last_beat = [time.monotonic()]


def heartbeat() -> None:
    """Mark forward progress.  Called on every span end and by step loops
    directly (engine.step, StepGuard.step), so the watchdog sees progress
    even with tracing disabled."""
    _last_beat[0] = time.monotonic()


def last_activity_age() -> float:
    """Seconds since the last heartbeat (span end / step completion)."""
    return time.monotonic() - _last_beat[0]


# -- the span ---------------------------------------------------------------

class Span:
    """One timed operation.  Mutable until ``end()``; recorded (trace
    store + flight ring) exactly once, at end."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "_t0", "ts_us", "dur_us", "tid", "_done")

    def __init__(self, name, trace_id, parent_id, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _next_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self._t0 = time.perf_counter_ns()
        self.ts_us = self._t0 / 1000.0   # RecordEvent's timebase
        self.dur_us = None
        self.tid = threading.get_ident() % 1_000_000
        self._done = False

    def end(self, **attrs) -> "Span":
        """Close the span (idempotent) and record it.  Late attributes
        (token counts, finish reason) merge into ``attrs`` here."""
        if self._done:
            return self
        self._done = True
        self.dur_us = (time.perf_counter_ns() - self._t0) / 1000.0
        if attrs:
            self.attrs.update(attrs)
        _record(self)
        heartbeat()
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts_us": self.ts_us,
            "dur_us": self.dur_us,
            "tid": self.tid,
            "attrs": dict(self.attrs),
        }

    def __repr__(self):
        state = f"{self.dur_us:.1f}us" if self._done else "open"
        return f"Span({self.name}, {self.span_id}, {state})"


class _NullSpan:
    """The disabled fast path: every producer API returns this singleton,
    whose methods are no-ops (attribute constants keep reads safe)."""

    __slots__ = ()
    name = trace_id = span_id = parent_id = None
    dur_us = ts_us = None

    def end(self, **attrs):
        return self

    def to_dict(self):
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False   # `if req.trace:` guards stay cheap and correct


_NULL = _NullSpan()


# -- storage ----------------------------------------------------------------
_MAX_TRACES = int(os.environ.get("PTPU_TRACE_MAX_TRACES", "256"))
_traces: "OrderedDict[str, list]" = OrderedDict()
_store_lock = threading.Lock()


# -- tail-based sampling (ISSUE 16) -----------------------------------------

def _env_tail() -> "int | None":
    raw = os.environ.get("PTPU_TRACE_TAIL", "").strip()
    if not raw or raw.lower() in ("off", "false"):
        return None
    try:
        return max(0, int(raw))
    except ValueError:
        return None


_tail_budget = _env_tail()      # None = sampling off (keep everything)
_TAIL_WINDOW_S = 60.0
# [window start (monotonic), boring traces kept this window]; mutated
# only under _store_lock
_tail_state = [0.0, 0]


def tail_budget() -> "int | None":
    """The boring-traces-kept-per-minute budget (None = sampling off)."""
    return _tail_budget


def set_tail_budget(budget: "int | None") -> None:
    """Set/clear the tail-sampling budget at runtime (overrides
    PTPU_TRACE_TAIL; None disables sampling, 0 keeps only interesting
    traces)."""
    global _tail_budget
    _tail_budget = None if budget is None else max(0, int(budget))


def _interesting(spans, root) -> bool:
    """Always-keep predicate, evaluated with the FULL trace in hand."""
    attrs = root["attrs"]
    if attrs.get("error") or attrs.get("keep"):
        return True
    fin = attrs.get("finish")
    if fin is not None and fin != "stop":
        return True
    for d in spans:
        if d["attrs"].get("error"):
            return True
    return False


def _tail_keep(spans, root) -> bool:
    """Keep decision for one finished root (call under _store_lock)."""
    if _interesting(spans, root):
        return True
    now = time.monotonic()
    if now - _tail_state[0] >= _TAIL_WINDOW_S:
        _tail_state[0] = now
        _tail_state[1] = 0
    if _tail_state[1] < _tail_budget:
        _tail_state[1] += 1
        return True
    return False


def _record(s: Span) -> None:
    d = s.to_dict()
    dropped = False
    with _store_lock:
        spans = _traces.get(s.trace_id)
        if spans is None:
            spans = _traces[s.trace_id] = []
            while len(_traces) > _MAX_TRACES:
                _traces.popitem(last=False)
        spans.append(d)
        # root ended → the trace is complete; with sampling on, decide
        # NOW whether the whole tree stays in the store
        if _tail_budget is not None and s.parent_id is None:
            if not _tail_keep(spans, d):
                _traces.pop(s.trace_id, None)
                dropped = True
    from . import flight

    flight.record_span(d)
    if _tail_budget is not None and s.parent_id is None:
        from . import counter

        if dropped:
            counter("trace/tail_dropped",
                    "boring traces dropped by tail sampling").inc()
        else:
            counter("trace/tail_kept",
                    "traces kept by tail sampling").inc()


def get_trace(trace_id: str) -> list:
    """Every finished span of one trace (start-ordered span dicts);
    [] for an unknown/evicted id."""
    with _store_lock:
        spans = list(_traces.get(trace_id, ()))
    return sorted(spans, key=lambda d: d["ts_us"])


def trace_ids() -> list:
    """Known trace ids, oldest first."""
    with _store_lock:
        return list(_traces)


def reset() -> None:
    """Drop every stored trace (tests)."""
    with _store_lock:
        _traces.clear()


# -- context propagation ----------------------------------------------------

class SpanContext:
    """Span *identity* without the span: what travels on a wire.  An
    ``extract()``-ed context carries only (trace_id, span_id); it can be
    adopted with :class:`attach` or passed as ``parent=`` so work in a
    DIFFERENT process lands as a child in the originating trace.  It is
    never recorded itself — only real spans are."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


# traceparent-style header: "<version>;<trace_id>;<span_id>".  ";" because
# our ids themselves contain "-" (W3C traceparent's separator).
_CTX_VERSION = "ptpu1"


def inject(span_=None) -> "str | None":
    """Serialize the current (or given) span's context for the wire —
    the client half of cross-process propagation.  Returns None when
    tracing is disabled or there is no open span, so a disabled caller
    attaches nothing (allocation-free, same budget as a disabled
    ``span()``; gated by bench.py --config trace_overhead)."""
    if not _enabled:
        return None
    s = _ctx.span if span_ is None else span_
    if s is None or s.trace_id is None:
        return None
    return f"{_CTX_VERSION};{s.trace_id};{s.span_id}"


def extract(header) -> "SpanContext | None":
    """Parse an :func:`inject`-ed header back into a SpanContext — the
    server half.  None for a missing/foreign/malformed header, and when
    tracing is disabled here (a receiver with PTPU_TRACE=0 must not pay
    for a sender's tracing).  The no-header path is allocation-free."""
    if not _enabled or not header:
        return None
    parts = header.split(";")
    if len(parts) != 3 or parts[0] != _CTX_VERSION \
            or not parts[1] or not parts[2]:
        return None
    return SpanContext(parts[1], parts[2])


class _Ctx(threading.local):
    span = None


_ctx = _Ctx()


def current_span():
    """The innermost open span() on THIS thread (None outside any)."""
    return _ctx.span


class attach:
    """Adopt `parent` — a Span from another thread, or a SpanContext
    ``extract()``-ed from another process — as this thread's current::

        ctx = trace.current_span()          # producer thread
        ...
        with trace.attach(ctx):             # worker thread
            with trace.span("load_batch"):  # lands under ctx's trace
                ...
    """

    __slots__ = ("_span", "_prev")

    def __init__(self, span_):
        self._span = span_ if isinstance(span_, (Span, SpanContext)) \
            else None

    def __enter__(self):
        self._prev = _ctx.span
        if self._span is not None:
            _ctx.span = self._span
        return self._span

    def __exit__(self, *exc):
        _ctx.span = self._prev
        return False


def start_span(name: str, parent=None, trace_id=None, **attrs):
    """Manual span (caller owns ``end()``).  ``parent`` may be a Span or
    a cross-process SpanContext; with neither parent nor trace_id a NEW
    trace is opened (the span is its root).  Returns the no-op singleton
    when tracing is disabled."""
    if not _enabled:
        return _NULL
    parent_id = None
    if isinstance(parent, (Span, SpanContext)):
        parent_id = parent.span_id
        trace_id = trace_id or parent.trace_id
    if trace_id is None:
        trace_id = _next_id("t")
    return Span(name, trace_id, parent_id, attrs)


class _Active:
    """span()'s handle: installs the span as the thread-local current on
    enter, restores the previous on exit, ends with error annotation."""

    __slots__ = ("_span", "_prev")

    def __init__(self, s):
        self._span = s

    def __enter__(self):
        self._prev = _ctx.span
        _ctx.span = self._span
        return self._span

    def __exit__(self, etype, evalue, tb):
        _ctx.span = self._prev
        if etype is not None:
            self._span.end(error=etype.__name__)
        else:
            self._span.end()
        return False


def span(name: str, **attrs):
    """Context-manager span, auto-parented under the thread's current
    span (a new trace when there is none)::

        with trace.span("resilience/ckpt_save", step=10):
            ...
    """
    if not _enabled:
        return _NULL
    return _Active(start_span(name, parent=_ctx.span, **attrs))


# -- chrome/Perfetto export -------------------------------------------------

def chrome_events(trace_id=None) -> list:
    """Finished spans as chrome ``trace_event`` dicts (phase "X").
    Identity rides ``args`` so Perfetto's flow/query UI can group by
    trace_id; ts/dur are in µs on the perf_counter timebase — the SAME
    base as profiler.RecordEvent host events."""
    pid = os.getpid()
    with _store_lock:
        if trace_id is not None:
            groups = [list(_traces.get(trace_id, ()))]
        else:
            groups = [list(v) for v in _traces.values()]
    out = []
    for spans in groups:
        for d in spans:
            args = {"trace_id": d["trace_id"], "span_id": d["span_id"]}
            if d["parent_id"]:
                args["parent_id"] = d["parent_id"]
            args.update(d["attrs"])
            out.append({
                "name": d["name"], "ph": "X", "ts": d["ts_us"],
                "dur": d["dur_us"] or 0.0, "pid": pid, "tid": d["tid"],
                "args": args,
            })
    return out


def export_chrome_trace(path: str, include_host_tracer: bool = True) -> str:
    """Write every stored span as a Chrome/Perfetto-loadable JSON file,
    merged with the profiler host tracer's RecordEvent spans when that
    module is loaded (``sys.modules`` probe — exporting a trace must
    never be the thing that initializes jax)."""
    events = chrome_events()
    if include_host_tracer:
        prof = sys.modules.get("paddle_tpu.profiler")
        if prof is not None:
            events = events + list(prof._tracer.events)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
